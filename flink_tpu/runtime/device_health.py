"""Device-lane health: watchdog, failure classification, quarantine, healing.

The accelerator is a failure domain, supervised the way the reference
runtime supervises a TaskManager (PAPER §5.3 failure detection / elastic
recovery): **detect** a stuck or failing device dispatch, **classify** the
failure, **quarantine** the device tier process-wide when it is wedged,
**degrade** the affected operators onto their host tier mid-job, and
**heal** — a background prober re-checks the backend and operators
re-promote their state at the next checkpoint-aligned safe point.

Why process-wide: the documented wedge mode of the tunnel transport
(VERDICT r5 weak #1) is a *device grant* that is never released — once one
dispatch hangs, **every** dispatch in the process hangs.  One monitor
therefore guards all device lanes (window hot path, mesh, evicting
windows, the bench's pre-flight probe) and one quarantine verdict is
shared by all of them.

Mechanics:

- :meth:`DeviceHealthMonitor.run_guarded` executes a dispatch thunk on a
  per-task-thread **lane thread** and waits with a bounded deadline
  derived from the measured dispatch cost (``utils/transport.py``, the
  PR-3 sync calibration) × a generous multiplier, floored by
  ``deadline_floor_s``.  A dispatch that misses the deadline is a
  **wedge**: the lane thread is *sacrificed* (abandoned where it blocks —
  nothing can unblock a hung ``block_until_ready``), a fresh lane serves
  later attempts, and the device tier is quarantined.  The task mailbox
  thread never blocks unboundedly.
- Failures raised by the dispatch are classified: **OOM**
  (RESOURCE_EXHAUSTED / out-of-memory) invokes the caller's ``on_oom``
  hook (the window operator forces a page-out through its DevicePager)
  and retries once; **transient** XLA/runtime errors retry under
  exponential backoff with jitter; anything else (shape errors, user
  bugs) re-raises unchanged — the watchdog must not convert programming
  errors into retries.  Exhausted retries quarantine.
- Healing probes the backend in a **throwaway subprocess** with its own
  process group (``probe_backend_subprocess``) under exponential backoff
  — never in-process (a probe that wedges would take the runtime with
  it) and never leaving orphaned jax helpers (``reap_process_group``:
  SIGTERM the group first, SIGKILL after a grace period — a KILLed
  client never releases its device grant, which is the wedge trigger
  itself).  On success the monitor returns HEALTHY and bumps the heal
  counter; operators poll :attr:`healthy` at checkpoint-aligned safe
  points to re-promote state.

Chaos: the lane fires the ``device.dispatch`` fault point *before*
invoking the thunk, so a :class:`~flink_tpu.testing.chaos.WedgedDevice`
schedule hangs exactly where a real wedge would, without the real
dispatch ever mutating (donated) device buffers — after the watchdog
abandons the attempt, the parked lane wakes on heal, sees the attempt
was abandoned and **skips** the dispatch.  The default probe consults the
same schedule (``chaos_aware_probe``), so the whole
quarantine→degrade→heal→re-promote cycle is testable on CPU.
"""

from __future__ import annotations

import os
import queue
import random
import re
import sys
import threading
import time
import dataclasses
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

from flink_tpu.observability import tracing
from flink_tpu.testing import chaos

__all__ = [
    "WatchdogConfig", "DeviceHealthMonitor", "DeviceQuarantinedError",
    "TRANSIENT", "OOM", "WEDGE", "FATAL", "classify_failure",
    "probe_backend_subprocess", "reap_process_group", "chaos_aware_probe",
    "get_monitor", "set_monitor", "reset_monitor", "guarded_dispatch",
    "status_snapshot",
]

# failure classes
TRANSIENT = "transient"
OOM = "oom"
WEDGE = "wedge"
FATAL = "fatal"

HEALTHY = "healthy"
QUARANTINED = "quarantined"

#: substrings marking a device OOM (jax raises XlaRuntimeError with the
#: absl status code in the message); "oom" matches as a WORD only — a
#: plain substring check would read "boom"/"bloom" as memory pressure
_OOM_MARKERS = ("resource_exhausted", "out of memory")
_OOM_WORD = re.compile(r"\boom\b")
#: retryable infrastructure errors: absl STATUS CODES as jax emits them —
#: matched case-sensitively as words, so a user bug whose message merely
#: contains "internal"/"aborted"/"unknown" in prose stays FATAL
_TRANSIENT_STATUS = re.compile(
    r"\b(UNAVAILABLE|INTERNAL|ABORTED|DEADLINE_EXCEEDED|UNKNOWN)\b")
_TRANSIENT_PHRASES = ("failed to connect", "connection reset",
                      "socket closed", "transient")


class DeviceQuarantinedError(RuntimeError):
    """The device tier is quarantined: the dispatch did not (and will not)
    run.  Operators catch this to degrade onto their host tier; tasks
    without a host tier fail and take the normal restart path."""


def classify_failure(exc: BaseException) -> str:
    """Map a dispatch exception to TRANSIENT / OOM / FATAL.  Conservative:
    only errors that look like infrastructure failures are retryable —
    a shape mismatch or user bug must surface unchanged."""
    raw = f"{type(exc).__name__}: {exc}"
    msg = raw.lower()
    if any(m in msg for m in _OOM_MARKERS) or _OOM_WORD.search(msg):
        return OOM
    if isinstance(exc, chaos.InjectedFault):
        # injected faults default to transient unless their message says
        # otherwise (FailTimes(message=...) steers the classifier)
        return TRANSIENT
    # deliberately NO blanket XlaRuntimeError match: jax wraps
    # deterministic user bugs (INVALID_ARGUMENT shape errors) in the same
    # type — only the infrastructure STATUS CODES are retryable
    if _TRANSIENT_STATUS.search(raw) \
            or any(p in msg for p in _TRANSIENT_PHRASES):
        return TRANSIENT
    return FATAL


# ---------------------------------------------------------------------------
# subprocess probe + process-group reaping (shared by runtime and bench)
# ---------------------------------------------------------------------------

def reap_process_group(proc, term_grace_s: float = 30.0,
                       kill_grace_s: float = 10.0) -> None:
    """Terminate a probe and its WHOLE process group.  jax clients fork
    helpers (tunnel endpoints, compile workers); killing only the leader
    leaves orphans holding the device grant — the documented wedge
    trigger.  SIGTERM first: a KILLed client never releases its grant, so
    the reaper must not CAUSE the failure it exists to detect."""
    import signal

    def _signal_group(sig):
        try:
            os.killpg(proc.pid, sig)  # probe runs as its own session leader
        except (ProcessLookupError, PermissionError, OSError):
            try:
                proc.send_signal(sig)
            except Exception:  # noqa: BLE001 — already gone
                pass

    _signal_group(signal.SIGTERM)
    try:
        proc.wait(timeout=term_grace_s)
    except Exception:  # noqa: BLE001 — subprocess.TimeoutExpired
        _signal_group(signal.SIGKILL)
        try:
            proc.wait(timeout=kill_grace_s)
        except Exception:  # noqa: BLE001
            pass


def probe_backend_subprocess(timeout_s: float = 180.0) -> bool:
    """One throwaway-subprocess accelerator probe (own process group):
    True iff ``jax.devices()`` succeeds within the timeout.  The probe
    lives in a subprocess because a wedged backend hangs the caller —
    a timed-out probe is reaped, group and all."""
    import subprocess
    proc = subprocess.Popen(
        [sys.executable, "-c", "import jax; jax.devices()"],
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
        start_new_session=True)
    try:
        return proc.wait(timeout=timeout_s) == 0
    except subprocess.TimeoutExpired:
        reap_process_group(proc)
        return False


def chaos_aware_probe(timeout_s: float = 180.0) -> bool:
    """Default healer probe.  When a chaos schedule owns the
    ``device.dispatch`` point, its wedge state IS the device's health —
    consult it (deterministic, no subprocess) so the full heal cycle runs
    on CPU in tests.  Otherwise, the real subprocess probe."""
    inj = chaos.active()
    if inj is not None and inj.has_schedule("device.dispatch"):
        return not chaos.blocked("device.dispatch")
    return probe_backend_subprocess(timeout_s)


# ---------------------------------------------------------------------------
# monitor
# ---------------------------------------------------------------------------

@dataclass
class WatchdogConfig:
    #: hard deadline floor for one dispatch (seconds); the measured
    #: per-MB dispatch cost raises it, never lowers it below this.
    #: default_factory: the FLINK_TPU_WATCHDOG_FLOOR_S knob is read at
    #: CONSTRUCTION time, not module import — setting it after the (very
    #: early, transitive) import still takes effect
    deadline_floor_s: float = dataclasses.field(
        default_factory=lambda: float(os.environ.get(
            "FLINK_TPU_WATCHDOG_FLOOR_S", "120")))
    #: deadline = max(floor, measured_ms_per_mb * mb * multiplier)
    deadline_multiplier: float = 20.0
    #: the FIRST guarded dispatch additionally gets this grace: it carries
    #: XLA compilation (easily seconds), which must not read as a wedge
    first_dispatch_grace_s: float = 300.0
    #: a successful dispatch slower than this fraction of its deadline
    #: counts a watchdog NEAR MISS (the early-warning gauge)
    near_miss_frac: float = 0.5
    #: transient-error retry budget per guarded call
    max_transient_retries: int = 3
    backoff_initial_s: float = 0.05
    backoff_multiplier: float = 2.0
    backoff_max_s: float = 2.0
    #: jitter fraction applied to each backoff sleep (decorrelates
    #: retry storms across subtask threads)
    backoff_jitter_frac: float = 0.25
    #: background healer probe cadence (exponential from initial to max)
    probe_backoff_initial_s: float = 0.5
    probe_backoff_max_s: float = 30.0
    probe_timeout_s: float = 180.0


class _Attempt:
    __slots__ = ("fn", "done", "result", "error", "abandoned",
                 "fire_chaos")

    def __init__(self, fn, fire_chaos: bool = True):
        self.fn = fn
        self.done = threading.Event()
        self.result = None
        self.error: Optional[BaseException] = None
        self.abandoned = False
        #: salvage reads skip the ``device.dispatch`` fault point: the
        #: chaos wedge models a hung DISPATCH grant, and the migration's
        #: state download must be drivable in the simulation (a REAL
        #: wedge hangs the read itself — the salvage deadline covers it)
        self.fire_chaos = fire_chaos


class _Lane:
    """One sacrificial dispatch thread.  The guarded call submits an
    attempt and waits with a deadline; a wedged attempt is abandoned in
    place (``die()``), and the owner creates a fresh lane.  The chaos
    ``device.dispatch`` point fires ON the lane, before the thunk — an
    abandoned attempt that later unwedges skips its thunk, so a
    quarantine-migrated operator's donated device buffers are never
    mutated behind its back."""

    def __init__(self, name: str):
        self._q: "queue.Queue[Optional[_Attempt]]" = queue.Queue()
        self._dead = False
        self._t = threading.Thread(target=self._loop, daemon=True,
                                   name=name)
        self._t.start()

    def _loop(self) -> None:
        while True:
            att = self._q.get()
            if att is None:
                return
            try:
                if att.fire_chaos:
                    chaos.fire("device.dispatch")
                if not att.abandoned:
                    att.result = att.fn()
            except BaseException as e:  # noqa: BLE001 — handed to the waiter
                att.error = e
            finally:
                att.done.set()
            if self._dead:
                return

    def submit(self, fn, fire_chaos: bool = True) -> _Attempt:
        att = _Attempt(fn, fire_chaos=fire_chaos)
        self._q.put(att)
        return att

    def die(self) -> None:
        """Abandon the lane where it blocks (sacrificial thread)."""
        self._dead = True
        self._q.put(None)   # if it ever drains, it exits


class DeviceHealthMonitor:
    """Supervision of the process's device tier — see module docstring.

    Thread-safe; one instance is shared process-wide (``get_monitor``).
    ``probe_fn`` and ``sleep`` are injectable for tests; ``heal_async``
    False disables the background healer (the owner drives
    :meth:`probe_now` itself — the bench does)."""

    def __init__(self, config: Optional[WatchdogConfig] = None,
                 probe_fn: Optional[Callable[[], bool]] = None,
                 sleep: Callable[[float], None] = time.sleep,
                 heal_async: bool = True):
        self.config = config or WatchdogConfig()
        self.probe_fn = probe_fn or (
            lambda: chaos_aware_probe(self.config.probe_timeout_s))
        self._sleep = sleep
        self.heal_async = heal_async
        self._lock = threading.Lock()
        self._state = HEALTHY
        #: task thread ident -> (owning thread, its lane); pruned on
        #: lookup when the owning thread died, so long-lived processes
        #: running many jobs don't accumulate parked lane threads
        self._lanes: Dict[int, tuple] = {}
        self._healer: Optional[threading.Thread] = None
        self._rng = random.Random(0xD15EA5E)
        self.last_failure: Optional[str] = None
        self.counters: Dict[str, int] = {
            "dispatches": 0, "quarantines": 0, "heals": 0,
            "watchdog_timeouts": 0, "transient_retries": 0,
            "oom_pageouts": 0, "near_misses": 0, "probe_attempts": 0,
        }
        #: guarded dispatches per label (e.g. "win.fused_scan") — the
        #: fused-megastep era's dispatch accounting: dispatches/batch is
        #: the metric the one-dispatch scan lane exists to shrink, and the
        #: per-site breakdown shows WHICH dispatch a regression added
        self.label_counts: Dict[str, int] = {}

    # -- state ---------------------------------------------------------------
    @property
    def state(self) -> str:
        return self._state

    @property
    def healthy(self) -> bool:
        return self._state == HEALTHY

    @property
    def quarantined(self) -> bool:
        return self._state == QUARANTINED

    def status(self) -> Dict[str, Any]:
        """Monitoring view: ``job_status()["device_health"]`` and the
        ``device_health.*`` gauges read this."""
        with self._lock:
            return {"state": self._state,
                    "last_failure": self.last_failure,
                    "deadline_floor_s": self.config.deadline_floor_s,
                    "dispatch_labels": dict(self.label_counts),
                    **dict(self.counters)}

    # -- watchdog ------------------------------------------------------------
    def deadline_s(self, mb: float = 0.0) -> float:
        """Dispatch deadline: measured cost (PR-3 sync calibration —
        ``transport.dispatch_ms_per_mb``) × generous multiplier, floored."""
        from flink_tpu.utils import transport
        per_mb = transport.dispatch_ms_per_mb()
        measured = 0.0
        if per_mb is not None and mb > 0:
            measured = per_mb * mb * self.config.deadline_multiplier / 1e3
        return max(self.config.deadline_floor_s, measured)

    def _lane(self) -> _Lane:
        cur = threading.current_thread()
        with self._lock:
            for tid, (thr, lane) in list(self._lanes.items()):
                if not thr.is_alive():
                    del self._lanes[tid]
                    lane.die()
            ent = self._lanes.get(cur.ident)
            if ent is None:
                lane = _Lane(f"device-lane-{len(self._lanes)}")
                self._lanes[cur.ident] = (cur, lane)
                return lane
            return ent[1]

    def _replace_lane(self) -> None:
        tid = threading.get_ident()
        with self._lock:
            ent = self._lanes.pop(tid, None)
        if ent is not None:
            ent[1].die()

    def run_guarded(self, fn: Callable[[], Any], mb: float = 0.0,
                    on_oom: Optional[Callable[[], None]] = None,
                    label: str = "dispatch",
                    compile_grace: bool = False) -> Any:
        """Run one device dispatch under the watchdog.  Returns ``fn()``'s
        result; raises :class:`DeviceQuarantinedError` when the tier is
        (or becomes) quarantined; re-raises FATAL errors unchanged.

        ``compile_grace``: the caller knows this dispatch will (re)compile
        — array geometry changed (state growth, a new operator's first
        batch) — so the deadline is raised to the compile grace; XLA
        recompiles happen on EVERY geometry change, not just the process's
        first dispatch, and must never read as a wedge."""
        if self.quarantined:
            raise DeviceQuarantinedError(
                f"device tier quarantined ({self.last_failure})")
        deadline = self.deadline_s(mb)
        backoff = self.config.backoff_initial_s
        retries = 0
        oom_retries = 0
        while True:
            with self._lock:
                self.counters["dispatches"] += 1
                self.label_counts[label] = \
                    self.label_counts.get(label, 0) + 1
                if compile_grace or self.counters["dispatches"] == 1:
                    deadline = max(deadline,
                                   self.config.first_dispatch_grace_s)
            lane = self._lane()
            att = lane.submit(fn)
            t0 = time.monotonic()
            if not att.done.wait(timeout=deadline):
                # WEDGE: sacrifice the lane, quarantine the tier
                att.abandoned = True
                self._replace_lane()
                with self._lock:
                    self.counters["watchdog_timeouts"] += 1
                tracing.instant("device_health.wedge", cat="device_health",
                                label=label, deadline_s=round(deadline, 1))
                self._quarantine(f"{label} exceeded {deadline:.1f}s "
                                 f"watchdog deadline (wedged)")
                raise DeviceQuarantinedError(
                    f"device tier quarantined ({self.last_failure})")
            elapsed = time.monotonic() - t0
            if att.error is None:
                if elapsed > deadline * self.config.near_miss_frac:
                    with self._lock:
                        self.counters["near_misses"] += 1
                return att.result
            kind = classify_failure(att.error)
            if kind == FATAL:
                raise att.error
            if kind == OOM and on_oom is not None and oom_retries == 0:
                oom_retries += 1
                with self._lock:
                    self.counters["oom_pageouts"] += 1
                on_oom()        # forced page-out frees HBM; retry once
                continue
            # TRANSIENT (or OOM without a pressure valve): backoff + retry
            if retries >= self.config.max_transient_retries:
                self._quarantine(
                    f"{label} failed {retries + 1}x "
                    f"({type(att.error).__name__}: {att.error})")
                raise DeviceQuarantinedError(
                    f"device tier quarantined ({self.last_failure})")
            retries += 1
            with self._lock:
                self.counters["transient_retries"] += 1
                jitter = 1.0 + self.config.backoff_jitter_frac * \
                    (2.0 * self._rng.random() - 1.0)
            self._sleep(backoff * jitter)
            backoff = min(backoff * self.config.backoff_multiplier,
                          self.config.backoff_max_s)

    def run_salvage(self, fn: Callable[[], Any],
                    deadline_s: Optional[float] = None,
                    label: str = "salvage") -> Any:
        """Bounded best-effort device READ while (or after) quarantining —
        the tier-migration state download.  Unlike :meth:`run_guarded` it
        runs even when quarantined, never retries, and never re-counts a
        quarantine: on deadline the lane is sacrificed and the caller
        falls back to checkpoint recovery.  A REAL wedge hangs the read
        and trips the deadline; the chaos simulation's wedge pins only
        the dispatch fault point, so salvage (which skips it) completes
        and the degrade path stays drivable on CPU.

        Default deadline: the compile-grace bound, not the dispatch
        floor — the salvage gathers may compile their kernels first, and
        a last-ditch state rescue prefers bounded-but-generous over
        tight-but-lossy."""
        deadline = (max(self.config.deadline_floor_s,
                        self.config.first_dispatch_grace_s)
                    if deadline_s is None else deadline_s)
        t0 = time.perf_counter_ns()
        lane = self._lane()
        att = lane.submit(fn, fire_chaos=False)
        done = att.done.wait(timeout=deadline)
        tracing.complete("device_health.salvage", t0,
                         time.perf_counter_ns(), cat="device_health",
                         label=label, completed=bool(done))
        if not done:
            att.abandoned = True
            self._replace_lane()
            with self._lock:
                self.counters["watchdog_timeouts"] += 1
            raise DeviceQuarantinedError(
                f"{label}: device unresponsive during state salvage "
                f"({deadline:.1f}s)")
        if att.error is not None:
            raise att.error
        return att.result

    # -- quarantine / healing ------------------------------------------------
    def _quarantine(self, reason: str) -> None:
        start_healer = False
        with self._lock:
            if self._state != QUARANTINED:
                self._state = QUARANTINED
                self.counters["quarantines"] += 1
                start_healer = self.heal_async
                tracing.instant("device_health.quarantine",
                                cat="device_health", reason=reason)
            self.last_failure = reason
        if start_healer:
            self._start_healer()

    def quarantine(self, reason: str) -> None:
        """Externally observed wedge (e.g. the bench's pre-flight probe
        failed): same transition the watchdog takes."""
        self._quarantine(reason)

    def probe_now(self) -> bool:
        """One synchronous probe; flips the tier back to HEALTHY (and
        counts a heal) on success.  The healer thread calls this on a
        backoff loop; tests and the bench call it directly."""
        with self._lock:
            self.counters["probe_attempts"] += 1
        ok = False
        try:
            ok = bool(self.probe_fn())
        except Exception:  # noqa: BLE001 — a crashing probe is a failed probe
            ok = False
        if ok:
            with self._lock:
                if self._state == QUARANTINED:
                    self._state = HEALTHY
                    self.counters["heals"] += 1
                    tracing.instant("device_health.heal",
                                    cat="device_health")
        return ok

    def probe_with_backoff(self, attempts: int = 2,
                           backoff_s: Optional[float] = None,
                           on_retry: Optional[Callable[[int, float],
                                                       None]] = None) -> bool:
        """Bounded synchronous probe-retry (the bench's pre-flight guard
        calls this): probe, back off, re-probe — the first probe's
        graceful group SIGTERM is itself the tunnel re-initialization
        attempt.  ``on_retry(attempt_no, backoff_s)`` is called before
        each backoff sleep (progress logging)."""
        backoff = (self.config.probe_backoff_initial_s
                   if backoff_s is None else backoff_s)
        for i in range(max(1, attempts)):
            if self.probe_now():
                return True
            if i + 1 < attempts:
                if on_retry is not None:
                    on_retry(i + 1, backoff)
                self._sleep(backoff)
                backoff = min(backoff * 2, self.config.probe_backoff_max_s)
        return False

    def _start_healer(self) -> None:
        with self._lock:
            if self._healer is not None and self._healer.is_alive():
                return
            self._healer = threading.Thread(target=self._heal_loop,
                                            daemon=True,
                                            name="device-healer")
            self._healer.start()

    def _heal_loop(self) -> None:
        backoff = self.config.probe_backoff_initial_s
        while self.quarantined:
            if self.probe_now():
                return
            self._sleep(backoff)
            backoff = min(backoff * 2, self.config.probe_backoff_max_s)


# ---------------------------------------------------------------------------
# process-wide monitor
# ---------------------------------------------------------------------------

_MONITOR: Optional[DeviceHealthMonitor] = None
_MONITOR_LOCK = threading.Lock()


def get_monitor(create: bool = True) -> Optional[DeviceHealthMonitor]:
    """The process-wide monitor (lazily created).  Disabled entirely with
    ``FLINK_TPU_DEVICE_WATCHDOG=off`` — :func:`guarded_dispatch` then runs
    dispatches inline, unguarded (the pre-PR behaviour)."""
    global _MONITOR
    if os.environ.get("FLINK_TPU_DEVICE_WATCHDOG", "").lower() in (
            "off", "0", "false"):
        return None
    with _MONITOR_LOCK:
        if _MONITOR is None and create:
            _MONITOR = DeviceHealthMonitor()
        return _MONITOR


def set_monitor(monitor: Optional[DeviceHealthMonitor]) -> None:
    global _MONITOR
    with _MONITOR_LOCK:
        _MONITOR = monitor


def reset_monitor() -> None:
    set_monitor(None)


def guarded_dispatch(fn: Callable[[], Any], mb: float = 0.0,
                     on_oom: Optional[Callable[[], None]] = None,
                     label: str = "dispatch",
                     compile_grace: bool = False) -> Any:
    """Run ``fn`` under the process-wide monitor — a queue handoff to the
    caller's lane thread plus an Event wait per dispatch (tens of µs;
    negligible next to any real device step).  With the watchdog disabled
    (``FLINK_TPU_DEVICE_WATCHDOG=off``) the thunk runs inline and
    UNGUARDED, but the chaos fault point still fires — disabling the
    watchdog must not silently disarm an injected schedule."""
    mon = get_monitor()
    if mon is None:
        chaos.fire("device.dispatch")
        return fn()
    return mon.run_guarded(fn, mb=mb, on_oom=on_oom, label=label,
                           compile_grace=compile_grace)


def status_snapshot() -> Dict[str, Any]:
    """Status of the process-wide monitor — HEALTHY defaults when no
    monitor exists yet (``job_status()["device_health"]`` backing)."""
    mon = get_monitor(create=False)
    if mon is None:
        return {"state": HEALTHY, "last_failure": None, "quarantines": 0,
                "heals": 0, "watchdog_timeouts": 0, "transient_retries": 0,
                "oom_pageouts": 0, "near_misses": 0, "dispatches": 0,
                "probe_attempts": 0, "dispatch_labels": {}}
    return mon.status()
