"""File system connector: split-based source + two-phase-commit sink.

Source side is the FLIP-27 file source analog
(``flink-connectors/flink-connector-files``: ``FileSource`` +
``SplitEnumerator`` over file splits): one split per matched file, readers
track a **row position** so checkpoints capture exact resume points — the
executor snapshots ``reader.position`` per split and hands it back to
``open_split`` on restore (``SourceReader.snapshotState`` analog).

Sink side is the ``StreamingFileSink``/``FileSink`` two-phase commit:
records append to an in-progress part file; ``snapshot_state`` rolls it into
the *pending* set (pre-commit); ``notify_checkpoint_complete`` atomically
renames pending parts to their final names (commit).  A restore re-commits
pending parts from the snapshot and discards orphaned in-progress files —
exactly-once file output.
"""

from __future__ import annotations

import glob as _glob
import os
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from flink_tpu.connectors.sources import Source, SourceSplit
from flink_tpu.core.batch import RecordBatch, StreamElement
from flink_tpu.formats import reader_for, writer_for


class _PositionedFileReader:
    """Iterator over one file's batches; ``position`` = rows already emitted
    (checkpointable, consumed by ``open_split`` on restore)."""

    def __init__(self, source: "FileSource", path: str, start_row: int):
        self.position = int(start_row)
        self._it = source._read_file(path, start_row)

    def __iter__(self):
        return self

    def __next__(self) -> StreamElement:
        el = next(self._it)
        if isinstance(el, RecordBatch):
            self.position += len(el)
        return el


class FileSource(Source):
    """Reads a file, directory, or glob in ``csv``/``jsonl``/``ftb`` format.
    One split per file (``FileSourceSplit`` analog)."""

    def __init__(self, path: str, format: str = "csv",
                 timestamp_column: Optional[str] = None,
                 batch_size: int = 8192, **format_kwargs):
        self.path = path
        self.format = format
        self.timestamp_column = timestamp_column
        self.batch_size = batch_size
        self.format_kwargs = format_kwargs
        reader_for(format)  # validate eagerly

    def _files(self) -> List[str]:
        if os.path.isdir(self.path):
            fs = [os.path.join(self.path, f) for f in sorted(os.listdir(self.path))
                  if not f.startswith((".", "_"))]
        else:
            fs = sorted(_glob.glob(self.path)) or [self.path]
        files = [f for f in fs if os.path.isfile(f)]
        if not files and not os.path.isdir(self.path):
            # a typo'd path must fail loudly, not run an empty job to success
            raise FileNotFoundError(
                f"FileSource: no files match {self.path!r}")
        return files

    def create_splits(self, parallelism: int) -> List[SourceSplit]:
        files = self._files()
        return [FileSplit(self, i, len(files), path=f) for i, f in enumerate(files)]

    def _read_file(self, path: str, start_row: int) -> Iterator[StreamElement]:
        read = reader_for(self.format)
        kw = dict(self.format_kwargs)
        if self.format in ("csv", "jsonl"):
            kw.setdefault("batch_size", self.batch_size)
            kw["timestamp_column"] = self.timestamp_column
            kw["skip_rows"] = start_row
            yield from read(path, **kw)
        else:  # ftb: frame-level skip by rows
            skipped = 0
            for b in read(path, **kw):
                if skipped + len(b) <= start_row:
                    skipped += len(b)
                    continue
                if skipped < start_row:  # partial batch resume
                    b = b.take(np.arange(start_row - skipped, len(b)))
                    skipped = start_row
                yield b

    # stateful-reader protocol (used by the executor; falls back to
    # ``split.read()`` for sources that don't implement it)
    def open_split(self, split: "FileSplit",
                   position: Optional[int]) -> _PositionedFileReader:
        return _PositionedFileReader(self, split.path, position or 0)


@dataclass
class FileSplit(SourceSplit):
    path: str = ""

    @property
    def split_id(self) -> str:
        return self.path

    def read(self) -> Iterator[StreamElement]:
        return self.source.open_split(self, 0)


class FileSink:
    """Two-phase-commit file sink (``FileSink`` analog). Part file lifecycle:
    ``.inprogress`` → (snapshot) ``.pending-{n}`` → (notify complete) final.
    Cloned per parallel subtask (own attempt id + part counter)."""

    clone_per_subtask = True

    def on_cloned(self) -> None:
        import uuid

        self._attempt = uuid.uuid4().hex[:8]
        self._buf = []
        self._buf_rows = 0
        self._pending = []

    def __init__(self, directory: str, format: str = "csv",
                 rolling_records: int = 1 << 20, prefix: str = "part"):
        import uuid

        self.directory = directory
        self.format = format
        self.rolling_records = rolling_records
        self.prefix = prefix
        #: unique per sink attempt, so a restarted job never collides with an
        #: orphaned part file of a previous attempt (reference part files
        #: carry subtask + bucket uid for the same reason)
        self._attempt = uuid.uuid4().hex[:8]
        #: set by open(ctx); scopes part names AND orphan cleanup so parallel
        #: sink subtasks sharing a directory never delete each other's parts
        self._subtask_index = 0
        self._buf: List[RecordBatch] = []
        self._buf_rows = 0
        self._counter = 0
        self._pending: List[str] = []   # rolled, awaiting checkpoint-complete
        writer_for(format)
        os.makedirs(directory, exist_ok=True)

    # -- Sink interface ------------------------------------------------------
    def write_batch(self, batch: RecordBatch) -> None:
        if len(batch) == 0:
            return
        self._buf.append(batch)
        self._buf_rows += len(batch)
        if self._buf_rows >= self.rolling_records:
            self._roll()

    def open(self, ctx) -> None:
        self._subtask_index = getattr(ctx, "subtask_index", 0)

    def _part_name(self, n: int) -> str:
        return os.path.join(
            self.directory,
            f"{self.prefix}-s{self._subtask_index}-{self._attempt}-"
            f"{n:05d}.{self.format}")

    def _roll(self) -> None:
        """Write the buffer to a pending part file (pre-commit)."""
        if not self._buf:
            return
        pending = self._part_name(self._counter) + f".pending"
        writer_for(self.format)(self._buf, pending)
        self._pending.append(pending)
        self._counter += 1
        self._buf = []
        self._buf_rows = 0

    def flush(self) -> None:
        # bounded end-of-input: roll and commit immediately (no more barriers)
        self._roll()
        self.commit_pending()

    def close(self) -> None:
        pass

    # -- two-phase commit ----------------------------------------------------
    def snapshot_state(self) -> Dict[str, Any]:
        self._roll()
        return {"pending": list(self._pending), "counter": self._counter}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._counter = int(snap.get("counter", 0))
        # parts pending in a COMPLETED checkpoint belong to the output:
        # re-commit them (rename is idempotent — missing file = already done)
        self._pending = [p for p in snap.get("pending", [])
                         if os.path.exists(p)]
        self.commit_pending()
        # orphaned pending files from a FAILED epoch are not in the snapshot:
        # they must not leak into results. Scope to THIS subtask's slot of
        # THIS prefix — sibling subtasks and other sinks sharing the
        # directory own their own pending parts.
        scope = f"{self.prefix}-s{self._subtask_index}-"
        for f in os.listdir(self.directory):
            if f.endswith(".pending") and f.startswith(scope):
                os.remove(os.path.join(self.directory, f))

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        self.commit_pending()

    def commit_pending(self) -> None:
        for p in self._pending:
            final = p[: -len(".pending")]
            if os.path.exists(p):
                os.replace(p, final)
        self._pending = []

    # -- inspection ----------------------------------------------------------
    def committed_files(self) -> List[str]:
        return sorted(os.path.join(self.directory, f)
                      for f in os.listdir(self.directory)
                      if not f.endswith(".pending") and f.startswith(self.prefix))
