"""File system connector: split-based source + two-phase-commit sink.

Source side is the FLIP-27 file source analog
(``flink-connectors/flink-connector-files``: ``FileSource`` +
``SplitEnumerator`` over file splits): one split per matched file, readers
track a **row position** so checkpoints capture exact resume points — the
executor snapshots ``reader.position`` per split and hands it back to
``open_split`` on restore (``SourceReader.snapshotState`` analog).

Sink side is the ``StreamingFileSink``/``FileSink`` two-phase commit:
records append to an in-progress part file; ``snapshot_state`` rolls it into
the *pending* set (pre-commit); ``notify_checkpoint_complete`` atomically
renames pending parts to their final names (commit).  A restore re-commits
pending parts from the snapshot and discards orphaned in-progress files —
exactly-once file output.
"""

from __future__ import annotations

import glob as _glob
import os
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.connectors.sources import Source, SourceSplit
from flink_tpu.core.batch import RecordBatch, StreamElement
from flink_tpu.formats import reader_for, writer_for


class _PositionedFileReader:
    """Iterator over one file's batches; ``position`` = rows already emitted
    (checkpointable, consumed by ``open_split`` on restore)."""

    def __init__(self, source: "FileSource", path: str, start_row: int):
        self.position = int(start_row)
        self._it = source._read_file(path, start_row)

    def __iter__(self):
        return self

    def __next__(self) -> StreamElement:
        el = next(self._it)
        if isinstance(el, RecordBatch):
            self.position += len(el)
        return el


class FileSource(Source):
    """Reads a file, directory, or glob in ``csv``/``jsonl``/``ftb``/``seq`` format.
    One split per file (``FileSourceSplit`` analog)."""

    def __init__(self, path: str, format: str = "csv",
                 timestamp_column: Optional[str] = None,
                 batch_size: int = 8192, **format_kwargs):
        self.path = path
        self.format = format
        self.timestamp_column = timestamp_column
        self.batch_size = batch_size
        self.format_kwargs = format_kwargs
        reader_for(format)  # validate eagerly

    def _files(self) -> List[str]:
        if os.path.isdir(self.path):
            fs = [os.path.join(self.path, f) for f in sorted(os.listdir(self.path))
                  if not f.startswith((".", "_"))]
        else:
            fs = sorted(_glob.glob(self.path)) or [self.path]
        files = [f for f in fs if os.path.isfile(f)]
        if not files and not os.path.isdir(self.path):
            # a typo'd path must fail loudly, not run an empty job to success
            raise FileNotFoundError(
                f"FileSource: no files match {self.path!r}")
        return files

    def create_splits(self, parallelism: int) -> List[SourceSplit]:
        files = self._files()
        return [FileSplit(self, i, len(files), path=f) for i, f in enumerate(files)]

    def _read_file(self, path: str, start_row: int) -> Iterator[StreamElement]:
        read = reader_for(self.format)
        kw = dict(self.format_kwargs)
        if self.format in ("csv", "jsonl", "seq"):
            kw.setdefault("batch_size", self.batch_size)
            kw["timestamp_column"] = self.timestamp_column
            kw["skip_rows"] = start_row
            yield from read(path, **kw)
        else:  # ftb: frame-level skip by rows
            skipped = 0
            for b in read(path, **kw):
                if skipped + len(b) <= start_row:
                    skipped += len(b)
                    continue
                if skipped < start_row:  # partial batch resume
                    b = b.take(np.arange(start_row - skipped, len(b)))
                    skipped = start_row
                yield b

    # stateful-reader protocol (used by the executor; falls back to
    # ``split.read()`` for sources that don't implement it)
    def open_split(self, split: "FileSplit",
                   position: Optional[int]) -> _PositionedFileReader:
        return _PositionedFileReader(self, split.path, position or 0)


@dataclass
class FileSplit(SourceSplit):
    path: str = ""

    @property
    def split_id(self) -> str:
        return self.path

    def read(self) -> Iterator[StreamElement]:
        return self.source.open_split(self, 0)


@dataclass
class RollingPolicy:
    """When an in-progress part rolls (``DefaultRollingPolicy`` analog,
    ``flink-connector-files/.../sink/FileSink.java:1``): by rows, bytes, or
    age.  Every policy ALSO rolls at checkpoints — the exactly-once part
    lifecycle here binds parts to checkpoint ids (the reference's
    ``OnCheckpointRollingPolicy`` made universal; the reference's
    resumable in-progress writer — truncate-on-restore — is simplified
    away, at the cost of at least one part per checkpoint interval)."""

    max_rows: int = 1 << 20
    max_bytes: int = 128 << 20
    rollover_interval_ms: Optional[int] = None


class DateTimeBucketAssigner:
    """Per-row event-time buckets (``DateTimeBucketAssigner`` analog):
    rows land in ``<directory>/<strftime(fmt)>/part-...``."""

    def __init__(self, fmt: str = "%Y-%m-%d--%H"):
        self.fmt = fmt

    def __call__(self, batch: RecordBatch) -> List[str]:
        import datetime
        ts = batch.timestamps
        if ts is None:
            return [""] * len(batch)
        # strftime only the distinct SECONDS (bucket formats are >= 1s
        # resolution), not every row — batches land in a handful of buckets
        secs = np.asarray(ts, np.int64) // 1000
        uniq, inv = np.unique(secs, return_inverse=True)
        names = [datetime.datetime.fromtimestamp(
            int(s), tz=datetime.timezone.utc).strftime(self.fmt)
            for s in uniq.tolist()]
        return [names[i] for i in inv.tolist()]


class _InProgressPart:
    """One bucket's open part.  Row formats (csv/jsonl) STREAM to a real
    ``.inprogress`` file (bounded memory); bulk formats (ftb/avro) buffer
    batches and materialize at roll (the reference's row-encoded vs bulk
    writer split)."""

    def __init__(self, fmt: str, path: str, row_format: bool):
        self.fmt = fmt
        self.path = path                   # local .inprogress path
        self.row_format = row_format
        self.rows = 0
        self.bytes = 0
        self.created = time.time()
        self._buf: List[RecordBatch] = []
        self._fh = None
        self._columns: Optional[List[str]] = None

    def append(self, batch: RecordBatch) -> None:
        self.rows += len(batch)
        if not self.row_format:
            self._buf.append(batch)
            self.bytes += sum(np.asarray(v).nbytes
                              for v in batch.columns.values())
            return
        import csv as _csv
        import io
        import json as _json
        if self._fh is None:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            self._fh = open(self.path, "ab")
        out = io.StringIO()
        cols = {k: np.asarray(v) for k, v in batch.columns.items()}
        if self.fmt == "csv":
            # csv.writer for quoting/escaping (commas, quotes, newlines in
            # string values) — same dialect formats.write_csv produces
            if self._columns is None:
                self._columns = list(cols)
            cw = _csv.writer(out)
            if self.bytes == 0:
                cw.writerow(self._columns)
            for i in range(len(batch)):
                cw.writerow([_plain(cols[c][i]) for c in self._columns])
        else:                              # jsonl
            names = list(cols)
            for i in range(len(batch)):
                out.write(_json.dumps({c: _plain(cols[c][i])
                                       for c in names}) + "\n")
        data = out.getvalue().encode()
        self._fh.write(data)
        self.bytes += len(data)

    def finish(self) -> None:
        """Materialize/close the .inprogress file."""
        if self.row_format:
            if self._fh is not None:
                self._fh.flush()
                os.fsync(self._fh.fileno())
                self._fh.close()
                self._fh = None
            return
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        writer_for(self.fmt)(self._buf, self.path)
        self._buf = []

    def abandon(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None
        if os.path.exists(self.path):
            os.remove(self.path)


def _plain(v):
    if isinstance(v, np.generic):
        return v.item()
    if isinstance(v, np.ndarray):
        return v.tolist()                  # multi-dim column cell
    return v


class FileSink:
    """Exactly-once two-phase-commit file sink (``FileSink.java:1`` +
    ``StreamingFileSink`` analog).  Part lifecycle: a real
    ``.inprogress`` file per bucket → rolled (policy or checkpoint) into
    ``.pending`` bound to the checkpoint id of the snapshot that rolled it
    (``current_checkpoint_id()``) → finalized when THAT checkpoint (or a
    later one) completes.  A pending part of checkpoint N+1 is NOT
    committed by checkpoint N's notification — a restore to N after N+1
    fails would otherwise double its rows.  Restore re-commits the
    snapshot's pending groups (idempotent) and discards this subtask's
    orphaned in-progress/pending files.

    ``filesystem``: None writes to the local directory; an object with
    ``put_object(key, bytes)``/``list_keys(prefix)`` (the in-repo
    :class:`~flink_tpu.filesystems.s3.S3Client`) stages parts in the local
    ``directory`` and uploads on commit — the S3 committer pattern (no
    rename on object stores)."""

    clone_per_subtask = True

    def on_cloned(self) -> None:
        import uuid

        self._attempt = uuid.uuid4().hex[:8]
        self._parts = {}
        self._groups = []
        self._open_group = []

    def __init__(self, directory: str, format: str = "csv",
                 rolling_records: Optional[int] = None, prefix: str = "part",
                 rolling_policy: Optional[RollingPolicy] = None,
                 bucket_assigner=None, filesystem=None):
        import uuid

        self.directory = directory
        self.format = format
        self.prefix = prefix
        if rolling_policy is None:
            self.policy = RollingPolicy(max_rows=rolling_records or (1 << 20))
        elif rolling_records is not None:
            # never mutate the caller's (possibly shared) policy object
            import dataclasses
            self.policy = dataclasses.replace(rolling_policy,
                                              max_rows=rolling_records)
        else:
            self.policy = rolling_policy
        self.bucket_assigner = bucket_assigner
        self.fs = filesystem
        self._row_format = format in ("csv", "jsonl")
        #: unique per sink attempt, so a restarted job never collides with an
        #: orphaned part file of a previous attempt (reference part files
        #: carry subtask + bucket uid for the same reason)
        self._attempt = uuid.uuid4().hex[:8]
        #: set by open(ctx); scopes part names AND orphan cleanup so parallel
        #: sink subtasks sharing a directory never delete each other's parts
        self._subtask_index = 0
        self._counter = 0
        #: bucket -> open _InProgressPart
        self._parts: Dict[str, _InProgressPart] = {}
        #: rolled parts awaiting their checkpoint's completion:
        #: [(checkpoint_id | None, [(local_pending_path, final_name), ...])]
        self._groups: List[Tuple[Optional[int], List[Tuple[str, str]]]] = []
        #: parts rolled since the last snapshot (join the next group)
        self._open_group: List[Tuple[str, str]] = []
        writer_for(format)
        os.makedirs(directory, exist_ok=True)

    # -- Sink interface ------------------------------------------------------
    def open(self, ctx) -> None:
        self._subtask_index = getattr(ctx, "subtask_index", 0)

    def write_batch(self, batch: RecordBatch) -> None:
        if len(batch) == 0:
            return
        if self.bucket_assigner is None:
            self._write_bucket("", batch)
            return
        buckets = self.bucket_assigner(batch)
        if isinstance(buckets, str):
            self._write_bucket(buckets, batch)
            return
        arr = np.asarray(buckets)
        for b in sorted(set(arr.tolist())):
            self._write_bucket(str(b), batch.select(arr == b))

    def _write_bucket(self, bucket: str, batch: RecordBatch) -> None:
        part = self._parts.get(bucket)
        if part is None:
            part = self._parts[bucket] = _InProgressPart(
                self.format, self._local_path(bucket, self._counter)
                + ".inprogress", self._row_format)
            self._counter += 1
        part.append(batch)
        p = self.policy
        age_ms = (time.time() - part.created) * 1000.0
        if (part.rows >= p.max_rows or part.bytes >= p.max_bytes
                or (p.rollover_interval_ms is not None
                    and age_ms >= p.rollover_interval_ms)):
            self._roll_bucket(bucket)

    def _final_name(self, bucket: str, n: int) -> str:
        name = (f"{self.prefix}-s{self._subtask_index}-{self._attempt}-"
                f"{n:05d}.{self.format}")
        return f"{bucket}/{name}" if bucket else name

    def _local_path(self, bucket: str, n: int) -> str:
        return os.path.join(self.directory, self._final_name(bucket, n))

    def _roll_bucket(self, bucket: str) -> None:
        part = self._parts.pop(bucket, None)
        if part is None or part.rows == 0:
            if part is not None:
                part.abandon()
            return
        part.finish()
        base = part.path[: -len(".inprogress")]
        pending = base + ".pending"
        os.replace(part.path, pending)
        self._open_group.append(
            (pending, os.path.relpath(base, self.directory)))

    def _roll(self) -> None:
        for bucket in list(self._parts):
            self._roll_bucket(bucket)

    def flush(self) -> None:
        # bounded end-of-input: roll and commit immediately (no more barriers)
        self._roll()
        self._groups.append((None, self._open_group))
        self._open_group = []
        self.commit_pending()

    def close(self) -> None:
        pass

    # -- two-phase commit ----------------------------------------------------
    def snapshot_state(self) -> Dict[str, Any]:
        from flink_tpu.operators.base import current_checkpoint_id

        self._roll()
        if self._open_group:
            cp = current_checkpoint_id()
            if cp is None:
                # outside snapshot_scope the group cannot be bound to a
                # checkpoint: it will commit on the NEXT notification of ANY
                # checkpoint — weaker than the id-bound contract, so surface
                # the misuse (in-repo runtimes always set the scope)
                import warnings
                warnings.warn(
                    "FileSink.snapshot_state() called outside "
                    "snapshot_scope(checkpoint_id); pending parts commit on "
                    "the next notification instead of their own checkpoint",
                    RuntimeWarning, stacklevel=2)
            self._groups.append((cp, self._open_group))
            self._open_group = []
        return {"pending_groups": [(cp, list(parts))
                                   for cp, parts in self._groups],
                # legacy flat view (pre-r4 snapshots carried "pending")
                "pending": [p for _cp, parts in self._groups
                            for p, _f in parts],
                "counter": self._counter}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._counter = int(snap.get("counter", 0))
        if "pending_groups" in snap:
            self._groups = [(cp, [tuple(e) for e in parts])
                            for cp, parts in snap["pending_groups"]]
        else:
            self._groups = [(None, [(p, os.path.relpath(
                p[: -len(".pending")], self.directory))
                for p in snap.get("pending", [])])]
        # parts pending in a COMPLETED checkpoint belong to the output:
        # re-commit them all (idempotent — a missing staged file means the
        # commit already happened before the crash)
        self.commit_pending()
        # orphaned in-progress/pending files from a FAILED epoch are not in
        # the snapshot and must not leak into results.  Scope to THIS
        # subtask's slot of THIS prefix — sibling subtasks and other sinks
        # sharing the directory own their own parts.
        scope = f"{self.prefix}-s{self._subtask_index}-"
        for root, _dirs, files in os.walk(self.directory):
            for f in files:
                if (f.endswith((".pending", ".inprogress"))
                        and f.startswith(scope)):
                    os.remove(os.path.join(root, f))

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        self.commit_pending(checkpoint_id)

    def commit_pending(self, up_to_checkpoint: Optional[int] = None) -> None:
        """Finalize pending groups bound to checkpoints <= the completed id
        (None = everything: restore re-commit and bounded end-of-input)."""
        keep = []
        for cp, parts in self._groups:
            if (up_to_checkpoint is not None and cp is not None
                    and cp > up_to_checkpoint):
                keep.append((cp, parts))
                continue
            for pending, final_name in parts:
                if not os.path.exists(pending):
                    continue                       # already committed
                if self.fs is None:
                    os.replace(pending,
                               os.path.join(self.directory, final_name))
                else:
                    with open(pending, "rb") as f:
                        self.fs.put_object(final_name.replace(os.sep, "/"),
                                           f.read())
                    os.remove(pending)
        self._groups = keep

    # -- inspection ----------------------------------------------------------
    def committed_files(self) -> List[str]:
        if self.fs is not None:
            return sorted(k for k in self.fs.list_keys("")
                          if os.path.basename(k).startswith(self.prefix))
        out = []
        for root, _dirs, files in os.walk(self.directory):
            for f in files:
                if (f.startswith(self.prefix)
                        and not f.endswith((".pending", ".inprogress"))):
                    out.append(os.path.join(root, f))
        return sorted(out)
