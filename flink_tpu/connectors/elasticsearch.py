"""Elasticsearch connector: REST/JSON wire server, client, and sink.

Analog of ``flink-connectors/flink-connector-elasticsearch7``
(``ElasticsearchSink.java:63`` + ``BulkProcessor`` flushing): the sink
buffers index actions and flushes them as ``_bulk`` NDJSON requests —
at-least-once via flush-on-checkpoint, upgraded to effectively-once when a
deterministic ``id_column`` makes every retry an idempotent upsert (the
reference documents the same recipe).

Like the Kafka/Postgres connectors, the wire dialect is implemented from
the public HTTP API on BOTH sides: ``ElasticsearchServer`` is a minimal
single-node server (document CRUD, ``_bulk``, ``_search`` with match_all /
term queries, ``_count``) that real HTTP clients can talk to, and
``ElasticsearchClient`` is the urllib-based client the sink uses.
"""

from __future__ import annotations

import json
import threading
import urllib.parse
import urllib.request
import uuid
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional

import numpy as np


class ElasticsearchServer:
    """Minimal single-node ES: indices of ``_id -> _source`` documents."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Lock()
        #: index -> {_id: source dict}
        self.indices: Dict[str, Dict[str, dict]] = {}
        srv_self = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):  # silence request logging
                pass

            def _reply(self, code: int, body: dict) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _body(self) -> bytes:
                n = int(self.headers.get("Content-Length") or 0)
                return self.rfile.read(n) if n else b""

            def do_PUT(self):  # noqa: N802 — create index
                parts = self.path.strip("/").split("/")
                if len(parts) == 1 and parts[0]:
                    created = srv_self._create_index(parts[0])
                    self._reply(200, {"acknowledged": True,
                                      "index": parts[0],
                                      "created": created})
                elif len(parts) == 3 and parts[1] == "_doc":
                    doc = json.loads(self._body() or b"{}")
                    srv_self._put_doc(parts[0], parts[2], doc)
                    self._reply(200, {"_index": parts[0], "_id": parts[2],
                                      "result": "created"})
                else:
                    self._reply(400, {"error": "bad PUT path"})

            def do_DELETE(self):  # noqa: N802
                parts = self.path.strip("/").split("/")
                with srv_self._lock:
                    if len(parts) == 1 and parts[0] in srv_self.indices:
                        del srv_self.indices[parts[0]]
                        self._reply(200, {"acknowledged": True})
                    elif len(parts) == 3 and parts[1] == "_doc":
                        idx = srv_self.indices.get(parts[0], {})
                        existed = idx.pop(parts[2], None) is not None
                        self._reply(200 if existed else 404,
                                    {"result": "deleted" if existed
                                     else "not_found"})
                    else:
                        self._reply(404, {"error": "not found"})

            def do_GET(self):  # noqa: N802
                path = urllib.parse.urlparse(self.path)
                parts = path.path.strip("/").split("/")
                if len(parts) == 3 and parts[1] == "_doc":
                    with srv_self._lock:
                        doc = srv_self.indices.get(parts[0], {}) \
                            .get(parts[2])
                    if doc is None:
                        self._reply(404, {"found": False})
                    else:
                        self._reply(200, {"_index": parts[0],
                                          "_id": parts[2],
                                          "found": True, "_source": doc})
                elif len(parts) == 2 and parts[1] == "_count":
                    with srv_self._lock:
                        n = len(srv_self.indices.get(parts[0], {}))
                    self._reply(200, {"count": n})
                elif len(parts) == 2 and parts[1] == "_search":
                    self._search(parts[0], {})
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self):  # noqa: N802
                path = urllib.parse.urlparse(self.path)
                parts = path.path.strip("/").split("/")
                if parts == ["_bulk"] or (len(parts) == 2
                                          and parts[1] == "_bulk"):
                    default_index = parts[0] if len(parts) == 2 else None
                    self._bulk(default_index)
                elif len(parts) == 2 and parts[1] == "_search":
                    self._search(parts[0],
                                 json.loads(self._body() or b"{}"))
                elif len(parts) == 2 and parts[1] == "_doc":
                    doc = json.loads(self._body() or b"{}")
                    did = uuid.uuid4().hex
                    srv_self._put_doc(parts[0], did, doc)
                    self._reply(201, {"_index": parts[0], "_id": did,
                                      "result": "created"})
                else:
                    self._reply(404, {"error": "not found"})

            def _bulk(self, default_index: Optional[str]) -> None:
                lines = [ln for ln in self._body().split(b"\n") if ln]
                items: List[dict] = []
                errors = False
                i = 0
                while i < len(lines):
                    try:
                        action = json.loads(lines[i])
                    except ValueError:
                        self._reply(400, {"error": "malformed action line"})
                        return
                    op = next(iter(action))
                    if op not in ("index", "create", "update", "delete"):
                        # reject like real ES: an unknown op consuming the
                        # wrong number of lines would desync the whole
                        # action/source framing after it
                        self._reply(400, {"error":
                                          f"unknown bulk action {op!r}"})
                        return
                    meta = action[op] or {}
                    index = meta.get("_index", default_index)
                    did = meta.get("_id") or uuid.uuid4().hex
                    i += 1
                    if op in ("index", "create", "update"):
                        if i >= len(lines):
                            self._reply(400, {"error": "missing source"})
                            return
                        src = json.loads(lines[i])
                        i += 1
                        if op == "update":
                            src = src.get("doc", src)
                        status = srv_self._bulk_put(index, did, src, op)
                    else:           # delete
                        status = srv_self._bulk_delete(index, did)
                    errors |= status >= 400
                    items.append({op: {"_index": index, "_id": did,
                                       "status": status}})
                self._reply(200, {"errors": errors, "items": items})

            def _search(self, index: str, body: dict) -> None:
                size = int(body.get("size", 10))
                query = body.get("query", {"match_all": {}})
                with srv_self._lock:
                    docs = dict(srv_self.indices.get(index, {}))
                if "term" in query:
                    ((field, want),) = query["term"].items()
                    if isinstance(want, dict):
                        want = want.get("value")
                    docs = {k: v for k, v in docs.items()
                            if v.get(field) == want}
                hits = [{"_index": index, "_id": k, "_source": v}
                        for k, v in list(docs.items())[:size]]
                self._reply(200, {
                    "hits": {"total": {"value": len(docs)}, "hits": hits}})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    def _create_index(self, name: str) -> bool:
        with self._lock:
            if name in self.indices:
                return False
            self.indices[name] = {}
            return True

    def _put_doc(self, index: str, did: str, doc: dict) -> None:
        with self._lock:
            self.indices.setdefault(index, {})[did] = doc

    def _bulk_put(self, index, did, src, op) -> int:
        if index is None:
            return 400
        with self._lock:
            idx = self.indices.setdefault(index, {})
            if op == "create" and did in idx:
                return 409           # version conflict, like real ES
            if op == "update" and did in idx:
                merged = dict(idx[did])
                merged.update(src)
                idx[did] = merged
            else:
                idx[did] = src
        return 200

    def _bulk_delete(self, index, did) -> int:
        if index is None:
            return 400
        with self._lock:
            existed = self.indices.get(index, {}).pop(did, None)
        return 200 if existed is not None else 404

    def close(self) -> None:
        self._httpd.shutdown()


class ElasticsearchError(Exception):
    pass


class ElasticsearchClient:
    """urllib REST client (the RestHighLevelClient analog the sink uses)."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.base = f"http://{host}:{port}"
        self.timeout_s = timeout_s

    def _call(self, method: str, path: str,
              body: Optional[bytes] = None,
              content_type: str = "application/json") -> dict:
        req = urllib.request.Request(self.base + path, data=body,
                                     method=method)
        if body is not None:
            req.add_header("Content-Type", content_type)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            raise ElasticsearchError(
                f"{method} {path}: {e.code} {e.read()[:200]!r}") from e
        except urllib.error.URLError as e:
            # connection-level failure (refused / timeout / DNS): callers
            # handle ElasticsearchError, never a raw URLError
            raise ElasticsearchError(f"{method} {path}: {e.reason}") from e

    def create_index(self, index: str) -> None:
        self._call("PUT", f"/{index}")

    def bulk(self, actions: List[dict]) -> dict:
        """actions: [{"op": "index"|"create"|"delete"|"update",
        "index": .., "id": .. or None, "doc": {..}}]; raises on any
        item-level error (the sink's failure handler surface)."""
        lines = []
        for a in actions:
            meta = {"_index": a["index"]}
            if a.get("id") is not None:
                meta["_id"] = str(a["id"])
            lines.append(json.dumps({a.get("op", "index"): meta}))
            if a.get("op", "index") != "delete":
                doc = a["doc"]
                lines.append(json.dumps(
                    {"doc": doc} if a.get("op") == "update" else doc))
        body = ("\n".join(lines) + "\n").encode()
        res = self._call("POST", "/_bulk", body,
                         "application/x-ndjson")
        if res.get("errors"):
            bad = [it for it in res["items"]
                   for op in it.values() if op["status"] >= 400]
            raise ElasticsearchError(f"bulk failures: {bad[:3]}")
        return res

    def get(self, index: str, did: str) -> Optional[dict]:
        try:
            return self._call("GET", f"/{index}/_doc/{did}")["_source"]
        except ElasticsearchError:
            return None

    def count(self, index: str) -> int:
        return int(self._call("GET", f"/{index}/_count")["count"])

    def search(self, index: str, query: Optional[dict] = None,
               size: int = 10) -> List[dict]:
        body = json.dumps({"query": query or {"match_all": {}},
                           "size": size}).encode()
        res = self._call("POST", f"/{index}/_search", body)
        return [h["_source"] for h in res["hits"]["hits"]]


def _json_value(v):
    if isinstance(v, np.generic):
        return v.item()
    return v


class ElasticsearchSink:
    """Bulk-flushing sink (``ElasticsearchSink.java:63`` +
    ``BulkProcessorBuilder`` flush knobs): rows buffer into index actions,
    flushing at ``bulk_actions`` and on EVERY checkpoint
    (flush-on-checkpoint = at-least-once).  With ``id_column`` set, the
    document id is deterministic and replayed writes overwrite themselves —
    the reference's documented idempotent-upsert recipe for
    effectively-once delivery."""

    clone_per_subtask = True

    def __init__(self, host: str, port: int, index: str,
                 id_column: Optional[str] = None,
                 bulk_actions: int = 1000):
        self.host, self.port = host, port
        self.index = index
        self.id_column = id_column
        self.bulk_actions = bulk_actions
        self._client: Optional[ElasticsearchClient] = None
        self._buf: List[dict] = []
        self.documents_written = 0

    def _cli(self) -> ElasticsearchClient:
        if self._client is None:
            self._client = ElasticsearchClient(self.host, self.port)
        return self._client

    def open(self, ctx) -> None:
        self._cli()

    def write_batch(self, batch) -> None:
        if not len(batch):
            return
        for r in batch.to_rows():
            doc = {k: _json_value(v) for k, v in r.items()}
            self._buf.append({
                "op": "index", "index": self.index,
                "id": doc.get(self.id_column)
                if self.id_column is not None else None,
                "doc": doc})
        if len(self._buf) >= self.bulk_actions:
            self._flush()

    def _flush(self) -> None:
        if not self._buf:
            return
        self._cli().bulk(self._buf)
        self.documents_written += len(self._buf)
        self._buf = []

    def snapshot_state(self) -> Dict[str, Any]:
        # flush-on-checkpoint: everything before the barrier is durable in
        # ES before the checkpoint completes (at-least-once contract)
        self._flush()
        return {}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._buf = []

    def end_input(self) -> None:
        self._flush()

    def close(self) -> None:
        try:
            self._flush()
        except ElasticsearchError:
            pass
        self._client = None
