"""RabbitMQ connector: AMQP 0-9-1 wire broker, client, source and sink.

Analog of ``flink-connectors/flink-connector-rabbitmq`` (``RMQSource`` /
``RMQSink``): the sink publishes rows as JSON message bodies, the source
drains a queue with at-least-once acknowledgement semantics (messages ack
AFTER the checkpoint barrier, so a crash replays the unacked tail —
``RMQSource.acknowledgeSessionIDs``).

As with Kafka/Postgres/Elasticsearch, the wire dialect is implemented from
the public protocol spec on both sides: ``AmqpBroker`` speaks real AMQP
0-9-1 framing (protocol header, Connection.Start/Tune/Open,
Channel.Open, Queue.Declare, Basic.Publish/Get/Ack with content header +
body frames), so a real AMQP client library can complete the same
handshakes; ``AmqpClient`` is the socket client the connector uses.

Scope: the classes/methods the connector needs (connection, one channel,
durable-ignored queue declare, publish, pull-based get, ack).  Consumer
push (Basic.Consume/Deliver), exchanges beyond the default direct
exchange, and transactions are not implemented.
"""

from __future__ import annotations

import json
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

PROTOCOL_HEADER = b"AMQP\x00\x00\x09\x01"
FRAME_METHOD, FRAME_HEADER, FRAME_BODY, FRAME_HEARTBEAT = 1, 2, 3, 8
FRAME_END = 0xCE

# class ids
C_CONNECTION, C_CHANNEL, C_QUEUE, C_BASIC = 10, 20, 50, 60
# connection methods
M_START, M_START_OK, M_TUNE, M_TUNE_OK = 10, 11, 30, 31
M_OPEN, M_OPEN_OK, M_CLOSE, M_CLOSE_OK = 40, 41, 50, 51
# channel methods
M_CH_OPEN, M_CH_OPEN_OK = 10, 11
# queue methods
M_Q_DECLARE, M_Q_DECLARE_OK = 10, 11
# basic methods
M_B_PUBLISH, M_B_GET, M_B_GET_OK = 40, 70, 71
M_B_GET_EMPTY, M_B_ACK = 72, 80


class AmqpError(Exception):
    pass


# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------


def _short_str(s: str) -> bytes:
    b = s.encode()
    return struct.pack("B", len(b)) + b


def _long_str(b: bytes) -> bytes:
    return struct.pack(">I", len(b)) + b


def _read_short_str(data: bytes, pos: int) -> Tuple[str, int]:
    n = data[pos]
    return data[pos + 1:pos + 1 + n].decode(), pos + 1 + n


def _frame(ftype: int, channel: int, payload: bytes) -> bytes:
    return struct.pack(">BHI", ftype, channel, len(payload)) \
        + payload + bytes([FRAME_END])


def _method(class_id: int, method_id: int, args: bytes = b"") -> bytes:
    return struct.pack(">HH", class_id, method_id) + args


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _read_frame(sock: socket.socket) -> Optional[Tuple[int, int, bytes]]:
    hdr = _recv_exact(sock, 7)
    if hdr is None:
        return None
    ftype, channel, size = struct.unpack(">BHI", hdr)
    payload = _recv_exact(sock, size)
    end = _recv_exact(sock, 1)
    if payload is None or end is None:
        return None
    if end[0] != FRAME_END:
        raise AmqpError(f"bad frame end {end!r}")
    return ftype, channel, payload


# ---------------------------------------------------------------------------
# broker
# ---------------------------------------------------------------------------


class AmqpBroker:
    """Single-node AMQP 0-9-1 broker: named queues of (delivery_tag-less)
    message bodies on the default exchange (routing key = queue name)."""

    FRAME_MAX = 1 << 20

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Lock()
        self.queues: Dict[str, List[bytes]] = {}
        #: per-connection unacked messages: (conn id, delivery_tag) ->
        #: (queue, body) — un-acked messages REQUEUE when the connection
        #: drops (the at-least-once redelivery the source relies on)
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="amqp-broker", daemon=True)
        self._thread.start()

    def declare_queue(self, name: str) -> int:
        with self._lock:
            q = self.queues.setdefault(name, [])
            return len(q)

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    # -- connection state machine ------------------------------------------
    def _serve(self, sock: socket.socket) -> None:
        unacked: Dict[int, Tuple[str, bytes]] = {}
        try:
            hdr = _recv_exact(sock, 8)
            if hdr != PROTOCOL_HEADER:
                # spec: answer with the supported protocol header and close.
                # Drain the peer's unread bytes first — closing with data in
                # the receive buffer RSTs the connection and the peer may
                # never see the header
                try:
                    sock.sendall(PROTOCOL_HEADER)
                    sock.shutdown(socket.SHUT_WR)
                    sock.settimeout(1.0)
                    while sock.recv(4096):
                        pass
                except OSError:
                    pass
                finally:
                    sock.close()
                return
            # Connection.Start: version 0-9, empty server props,
            # PLAIN mechanism, en_US locales
            start = _method(C_CONNECTION, M_START,
                            struct.pack("BB", 0, 9) + _long_str(b"")
                            + _long_str(b"PLAIN") + _long_str(b"en_US"))
            sock.sendall(_frame(FRAME_METHOD, 0, start))
            self._expect(sock, C_CONNECTION, M_START_OK)
            tune = _method(C_CONNECTION, M_TUNE,
                           struct.pack(">HIH", 2047, self.FRAME_MAX, 0))
            sock.sendall(_frame(FRAME_METHOD, 0, tune))
            self._expect(sock, C_CONNECTION, M_TUNE_OK)
            self._expect(sock, C_CONNECTION, M_OPEN)
            sock.sendall(_frame(FRAME_METHOD, 0,
                                _method(C_CONNECTION, M_OPEN_OK,
                                        _short_str(""))))
            self._session(sock, unacked)
        except (OSError, AmqpError, _Closed):
            pass
        finally:
            # redeliver this connection's unacked messages (front of queue:
            # redelivery beats new arrivals, like a broker requeue)
            with self._lock:
                for tag in sorted(unacked, reverse=True):
                    qname, body = unacked[tag]
                    self.queues.setdefault(qname, []).insert(0, body)
            try:
                sock.close()
            except OSError:
                pass

    def _expect(self, sock, class_id: int, method_id: int) -> bytes:
        while True:
            fr = _read_frame(sock)
            if fr is None:
                raise _Closed()
            ftype, _ch, payload = fr
            if ftype == FRAME_HEARTBEAT:
                continue
            cid, mid = struct.unpack(">HH", payload[:4])
            if (cid, mid) != (class_id, method_id):
                raise AmqpError(f"expected {class_id}.{method_id}, "
                                f"got {cid}.{mid}")
            return payload[4:]

    def _session(self, sock: socket.socket,
                 unacked: Dict[int, Tuple[str, bytes]]) -> None:
        next_tag = 1
        pending_publish: Optional[Tuple[str, int]] = None  # (queue, size)
        pending_body = b""
        while True:
            fr = _read_frame(sock)
            if fr is None:
                raise _Closed()
            ftype, channel, payload = fr
            if ftype == FRAME_HEARTBEAT:
                continue
            if ftype == FRAME_HEADER and pending_publish is not None:
                # content header: class, weight, body size, property flags
                _cls, _w, size = struct.unpack(">HHQ", payload[:12])
                pending_publish = (pending_publish[0], size)
                if size == 0:
                    self._enqueue(pending_publish[0], b"")
                    pending_publish = None
                continue
            if ftype == FRAME_BODY and pending_publish is not None:
                pending_body += payload
                if len(pending_body) >= pending_publish[1]:
                    self._enqueue(pending_publish[0], pending_body)
                    pending_publish = None
                    pending_body = b""
                continue
            if ftype != FRAME_METHOD:
                raise AmqpError(f"unexpected frame type {ftype}")
            cid, mid = struct.unpack(">HH", payload[:4])
            args = payload[4:]
            if (cid, mid) == (C_CHANNEL, M_CH_OPEN):
                sock.sendall(_frame(FRAME_METHOD, channel,
                                    _method(C_CHANNEL, M_CH_OPEN_OK,
                                            _long_str(b""))))
            elif (cid, mid) == (C_QUEUE, M_Q_DECLARE):
                # ticket(2) queue(shortstr) flags(1) arguments(table)
                qname, _pos = _read_short_str(args, 2)
                n = self.declare_queue(qname)
                ok = _method(C_QUEUE, M_Q_DECLARE_OK,
                             _short_str(qname) + struct.pack(">II", n, 0))
                sock.sendall(_frame(FRAME_METHOD, channel, ok))
            elif (cid, mid) == (C_BASIC, M_B_PUBLISH):
                # ticket(2) exchange(shortstr) routing-key(shortstr) bits
                _ex, pos = _read_short_str(args, 2)
                rkey, _pos = _read_short_str(args, pos)
                pending_publish = (rkey, -1)
                pending_body = b""
            elif (cid, mid) == (C_BASIC, M_B_GET):
                # ticket(2) queue(shortstr) no-ack bit
                qname, pos = _read_short_str(args, 2)
                no_ack = bool(args[pos] & 1) if pos < len(args) else False
                with self._lock:
                    q = self.queues.get(qname, [])
                    body = q.pop(0) if q else None
                    remaining = len(q)
                if body is None:
                    sock.sendall(_frame(
                        FRAME_METHOD, channel,
                        _method(C_BASIC, M_B_GET_EMPTY, _short_str(""))))
                    continue
                tag = next_tag
                next_tag += 1
                if not no_ack:
                    unacked[tag] = (qname, body)
                ok = _method(C_BASIC, M_B_GET_OK,
                             struct.pack(">QB", tag, 0) + _short_str("")
                             + _short_str(qname)
                             + struct.pack(">I", remaining))
                hdr = struct.pack(">HHQH", C_BASIC, 0, len(body), 0)
                out = (_frame(FRAME_METHOD, channel, ok)
                       + _frame(FRAME_HEADER, channel, hdr))
                # bodies SPLIT at the negotiated frame-max (spec 4.2.3:
                # an oversized frame is a framing error to real clients)
                limit = self.FRAME_MAX - 8
                for lo in range(0, len(body), limit):
                    out += _frame(FRAME_BODY, channel, body[lo:lo + limit])
                sock.sendall(out)
            elif (cid, mid) == (C_BASIC, M_B_ACK):
                tag, bits = struct.unpack(">QB", args[:9])
                multiple = bool(bits & 1)
                if multiple:
                    for t in [t for t in unacked if t <= tag]:
                        unacked.pop(t)
                else:
                    unacked.pop(tag, None)
            elif (cid, mid) == (C_CONNECTION, M_CLOSE):
                sock.sendall(_frame(FRAME_METHOD, 0,
                                    _method(C_CONNECTION, M_CLOSE_OK)))
                return   # unacked messages REQUEUE (spec: closing a
                #          connection requeues; only Basic.Ack is final)
            else:
                raise AmqpError(f"unsupported method {cid}.{mid}")

    def _enqueue(self, queue: str, body: bytes) -> None:
        with self._lock:
            self.queues.setdefault(queue, []).append(body)


class _Closed(Exception):
    pass


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class AmqpClient:
    """Minimal AMQP 0-9-1 client: connection + one channel, declare /
    publish / get / ack."""

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout_s)
        try:
            self.sock.sendall(PROTOCOL_HEADER)
            self._expect(C_CONNECTION, M_START)
            # PLAIN response with empty credentials (the broker is open)
            start_ok = _method(
                C_CONNECTION, M_START_OK,
                _long_str(b"") + _short_str("PLAIN")
                + _long_str(b"\x00guest\x00guest") + _short_str("en_US"))
            self.sock.sendall(_frame(FRAME_METHOD, 0, start_ok))
            self._expect(C_CONNECTION, M_TUNE)
            self.sock.sendall(_frame(
                FRAME_METHOD, 0,
                _method(C_CONNECTION, M_TUNE_OK,
                        struct.pack(">HIH", 2047, AmqpBroker.FRAME_MAX,
                                    0))))
            self.sock.sendall(_frame(
                FRAME_METHOD, 0,
                _method(C_CONNECTION, M_OPEN, _short_str("/")
                        + _short_str("") + b"\x00")))
            self._expect(C_CONNECTION, M_OPEN_OK)
            self.sock.sendall(_frame(FRAME_METHOD, 1,
                                     _method(C_CHANNEL, M_CH_OPEN,
                                             _short_str(""))))
            self._expect(C_CHANNEL, M_CH_OPEN_OK)
        except BaseException:
            self.sock.close()
            raise

    def _expect(self, class_id: int, method_id: int) -> bytes:
        while True:
            fr = _read_frame(self.sock)
            if fr is None:
                raise AmqpError("connection closed")
            ftype, _ch, payload = fr
            if ftype == FRAME_HEARTBEAT:
                continue
            cid, mid = struct.unpack(">HH", payload[:4])
            if (cid, mid) != (class_id, method_id):
                raise AmqpError(f"expected {class_id}.{method_id}, "
                                f"got {cid}.{mid}")
            return payload[4:]

    def queue_declare(self, queue: str) -> int:
        """-> message count currently in the queue."""
        self.sock.sendall(_frame(
            FRAME_METHOD, 1,
            _method(C_QUEUE, M_Q_DECLARE,
                    b"\x00\x00" + _short_str(queue) + b"\x00"
                    + struct.pack(">I", 0))))
        args = self._expect(C_QUEUE, M_Q_DECLARE_OK)
        _name, pos = _read_short_str(args, 0)
        n, _c = struct.unpack(">II", args[pos:pos + 8])
        return n

    def publish(self, queue: str, body: bytes) -> None:
        pub = _method(C_BASIC, M_B_PUBLISH,
                      b"\x00\x00" + _short_str("") + _short_str(queue)
                      + b"\x00")
        hdr = struct.pack(">HHQH", C_BASIC, 0, len(body), 0)
        frames = (_frame(FRAME_METHOD, 1, pub)
                  + _frame(FRAME_HEADER, 1, hdr))
        limit = AmqpBroker.FRAME_MAX - 8     # split at the negotiated max
        for lo in range(0, len(body), limit):
            frames += _frame(FRAME_BODY, 1, body[lo:lo + limit])
        self.sock.sendall(frames)

    def get(self, queue: str, no_ack: bool = False
            ) -> Optional[Tuple[int, bytes]]:
        """-> (delivery_tag, body) or None when the queue is empty."""
        self.sock.sendall(_frame(
            FRAME_METHOD, 1,
            _method(C_BASIC, M_B_GET,
                    b"\x00\x00" + _short_str(queue)
                    + (b"\x01" if no_ack else b"\x00"))))
        fr = _read_frame(self.sock)
        if fr is None:
            raise AmqpError("connection closed")
        _ftype, _ch, payload = fr
        cid, mid = struct.unpack(">HH", payload[:4])
        if (cid, mid) == (C_BASIC, M_B_GET_EMPTY):
            return None
        if (cid, mid) != (C_BASIC, M_B_GET_OK):
            raise AmqpError(f"unexpected {cid}.{mid}")
        tag = struct.unpack(">Q", payload[4:12])[0]
        fr = _read_frame(self.sock)             # content header
        if fr is None:
            raise AmqpError("connection closed mid-get")
        size = struct.unpack(">HHQ", fr[2][:12])[2]
        body = b""
        while len(body) < size:
            fr = _read_frame(self.sock)
            if fr is None:
                raise AmqpError("connection closed mid-get")
            body += fr[2]
        return tag, body

    def ack(self, delivery_tag: int, multiple: bool = False) -> None:
        self.sock.sendall(_frame(
            FRAME_METHOD, 1,
            _method(C_BASIC, M_B_ACK,
                    struct.pack(">QB", delivery_tag,
                                1 if multiple else 0))))

    def close(self) -> None:
        try:
            self.sock.sendall(_frame(
                FRAME_METHOD, 0,
                _method(C_CONNECTION, M_CLOSE,
                        struct.pack(">H", 200) + _short_str("bye")
                        + struct.pack(">HH", 0, 0))))
            self._expect(C_CONNECTION, M_CLOSE_OK)
        except (OSError, AmqpError):
            pass
        self.sock.close()


# ---------------------------------------------------------------------------
# source / sink
# ---------------------------------------------------------------------------


class RmqSink:
    """``RMQSink`` analog: rows publish as JSON bodies (at-least-once)."""

    clone_per_subtask = True

    def __init__(self, host: str, port: int, queue: str):
        self.host, self.port, self.queue = host, port, queue
        self._client: Optional[AmqpClient] = None

    def _cli(self) -> AmqpClient:
        if self._client is None:
            self._client = AmqpClient(self.host, self.port)
            self._client.queue_declare(self.queue)
        return self._client

    def open(self, ctx) -> None:
        self._cli()

    def write_batch(self, batch) -> None:
        from flink_tpu.connectors.util import json_default
        c = self._cli()
        for r in batch.to_rows():
            c.publish(self.queue, json.dumps(
                r, default=json_default).encode())

    def close(self) -> None:
        if self._client is not None:
            self._client.close()
            self._client = None


class RmqSource:
    """``RMQSource`` analog: drain a queue, acking only when the drain
    COMPLETES — messages stay unacked for the whole read, so a crash
    anywhere mid-job redelivers everything (at-least-once; the reference
    gets exactly-once only with correlation ids + a dedup state, the same
    recipe a keyed dedup downstream gives here)."""

    bounded = True

    def __init__(self, host: str, port: int, queue: str,
                 batch_rows: int = 1024,
                 timestamp_column: Optional[str] = None):
        self.host, self.port, self.queue = host, port, queue
        self.batch_rows = batch_rows
        self.timestamp_column = timestamp_column

    def create_splits(self, parallelism: int):
        from flink_tpu.connectors.sources import SourceSplit

        src = self

        class _Split(SourceSplit):
            def split_id(_self) -> str:
                return f"{src.queue}-0"

            def read(_self):
                return src._drain()

        return [_Split(self, 0, 1)]

    def _drain(self):
        from flink_tpu.core.batch import RecordBatch

        c = AmqpClient(self.host, self.port)
        try:
            c.queue_declare(self.queue)
            rows: List[dict] = []
            last_tag: Optional[int] = None
            while True:
                got = c.get(self.queue)
                if got is None:
                    break
                tag, body = got
                rows.append(json.loads(body.decode()))
                last_tag = tag
                if len(rows) >= self.batch_rows:
                    yield self._batch(rows, RecordBatch)
                    rows = []
            if rows:
                yield self._batch(rows, RecordBatch)
            if last_tag is not None:
                # ack ONLY at full-drain completion: an earlier ack would
                # let a crash lose the acked tail before any checkpoint
                # covered it
                c.ack(last_tag, multiple=True)
        finally:
            c.close()

    def _batch(self, rows, _RecordBatch):
        from flink_tpu.connectors.util import rows_to_batch
        return rows_to_batch(rows, self.timestamp_column)
