"""Sources: batched record producers.

The unified source contract mirrors FLIP-27
(``flink-core/.../api/connector/source/Source.java``): a source exposes
*splits* via ``create_splits`` and readers turn a split into an ordered
iterator of ``StreamElement``s (RecordBatches + Watermarks).  The executor is
the ``SourceReaderBase``/``SourceOperator`` analog: it drains reader batches
through the pipeline.  Boundedness drives end-of-input handling
(``Boundedness.java``).
"""

from __future__ import annotations

import socket as _socket
import time
from dataclasses import dataclass
from typing import Any, Callable, Dict, Iterable, Iterator, List, Mapping, Optional, Sequence

import numpy as np

from flink_tpu.core.batch import RecordBatch, StreamElement, Watermark


class Source:
    """Base source. bounded=True sources end; unbounded ones run until a
    record budget/cancellation (the executor enforces budgets)."""

    bounded: bool = True

    def create_splits(self, parallelism: int) -> List["SourceSplit"]:
        """Partition the source into independent splits (``SplitEnumerator``)."""
        return [SourceSplit(self, 0, 1)]


@dataclass
class SourceSplit:
    """One independently readable partition of a source."""

    source: "Source"
    index: int
    of: int

    def read(self) -> Iterator[StreamElement]:
        return self.source.read_split(self.index, self.of)


def split_id_of(split) -> str:
    """Canonical split identity, shared by every runtime (reader-side
    finished/assigned bookkeeping, enumerator reclaim, executor position
    tracking must all key identically): a ``split_id`` method or plain
    string attribute wins, else ``index/of``."""
    sid = getattr(split, "split_id", None)
    if callable(sid):
        return sid()
    return sid if sid else f"{split.index}/{split.of}"


def _columns_from_rows(rows: Sequence[Mapping[str, Any]]) -> Dict[str, np.ndarray]:
    if not rows:
        return {}
    names = rows[0].keys()
    return {n: np.asarray([r[n] for r in rows]) for n in names}


class CollectionSource(Source):
    """Bounded in-memory source (``env.fromCollection`` analog). Accepts rows
    (list of dicts) or a columns mapping; optional timestamp column."""

    def __init__(self, rows: Optional[Sequence[Mapping[str, Any]]] = None,
                 columns: Optional[Mapping[str, Any]] = None,
                 timestamp_column: Optional[str] = None,
                 batch_size: int = 4096):
        if columns is None:
            columns = _columns_from_rows(rows or [])
        self.columns = {k: np.asarray(v) for k, v in columns.items()}
        self.timestamp_column = timestamp_column
        self.batch_size = batch_size
        n = 0
        for v in self.columns.values():
            n = len(v)
            break
        self.n = n

    def create_splits(self, parallelism: int) -> List[SourceSplit]:
        return [SourceSplit(self, i, parallelism) for i in range(parallelism)]

    def read_split(self, index: int, of: int) -> Iterator[StreamElement]:
        # contiguous range per split
        lo = self.n * index // of
        hi = self.n * (index + 1) // of
        for start in range(lo, hi, self.batch_size):
            stop = min(start + self.batch_size, hi)
            cols = {k: v[start:stop] for k, v in self.columns.items()}
            ts = (np.asarray(cols[self.timestamp_column], np.int64)
                  if self.timestamp_column else None)
            yield RecordBatch(cols, timestamps=ts)


class GeneratorSource(Source):
    """Data-generator source (``DataGeneratorSource`` analog): calls
    ``make_batch(split_index, batch_index, batch_size) -> columns dict`` until
    ``num_batches`` is reached."""

    def __init__(self, make_batch: Callable[[int, int, int], Mapping[str, Any]],
                 num_batches: int, batch_size: int = 4096,
                 timestamp_column: Optional[str] = None, bounded: bool = True):
        self.make_batch = make_batch
        self.num_batches = num_batches
        self.batch_size = batch_size
        self.timestamp_column = timestamp_column
        self.bounded = bounded

    def create_splits(self, parallelism: int) -> List[SourceSplit]:
        return [SourceSplit(self, i, parallelism) for i in range(parallelism)]

    def read_split(self, index: int, of: int) -> Iterator[StreamElement]:
        for b in range(index, self.num_batches, of):
            cols = dict(self.make_batch(index, b, self.batch_size))
            ts = (np.asarray(cols[self.timestamp_column], np.int64)
                  if self.timestamp_column else None)
            yield RecordBatch({k: np.asarray(v) for k, v in cols.items()},
                              timestamps=ts)


class SocketTextSource(Source):
    """``env.socketTextStream`` analog (baseline config #1 source): reads
    newline-delimited text from a TCP socket, emits ``{"line": ...}`` batches.
    Batches are cut by ``batch_size`` lines or ``linger_ms``, whichever first —
    the linger bound keeps fire latency low on slow streams."""

    bounded = False

    def __init__(self, host: str, port: int, batch_size: int = 4096,
                 linger_ms: int = 50, max_retries: int = 3):
        self.host, self.port = host, port
        self.batch_size = batch_size
        self.linger_ms = linger_ms
        self.max_retries = max_retries

    def read_split(self, index: int, of: int) -> Iterator[StreamElement]:
        if index != 0:
            return
        retries = 0
        while retries <= self.max_retries:
            try:
                with _socket.create_connection((self.host, self.port)) as sock:
                    sock.settimeout(self.linger_ms / 1000.0)
                    buf = b""
                    lines: List[str] = []
                    deadline = time.monotonic() + self.linger_ms / 1000.0
                    while True:
                        try:
                            data = sock.recv(1 << 16)
                            if not data:
                                break
                            buf += data
                            *complete, buf = buf.split(b"\n")
                            lines.extend(l.decode("utf-8", "replace")
                                         for l in complete)
                        except _socket.timeout:
                            pass
                        now = time.monotonic()
                        if lines and (len(lines) >= self.batch_size or now >= deadline):
                            chunk, lines = lines[: self.batch_size], lines[self.batch_size:]
                            yield RecordBatch({"line": np.asarray(chunk, object)})
                            deadline = now + self.linger_ms / 1000.0
                    if buf:
                        lines.append(buf.decode("utf-8", "replace"))
                    if lines:
                        yield RecordBatch({"line": np.asarray(lines, object)})
                    return
            except (ConnectionError, OSError):
                retries += 1
                time.sleep(0.2 * retries)


class IteratorSource(Source):
    """Wraps any iterator of pre-built StreamElements (testing / replay)."""

    def __init__(self, elements: Iterable[StreamElement], bounded: bool = True):
        self.elements = list(elements)
        self.bounded = bounded

    def read_split(self, index: int, of: int) -> Iterator[StreamElement]:
        if index == 0:
            yield from self.elements
