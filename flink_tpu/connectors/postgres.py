"""PostgreSQL wire protocol (v3): client + server + JDBC-analog connector.

The reference's JDBC connector
(``flink-connectors/flink-connector-jdbc/.../JdbcSink.java:37``,
``JdbcRowDataInputFormat``, Postgres dialect under ``catalog/`` and
``dialect/``) reaches relational stores through the JDBC driver stack.
There is no JVM here, so this module implements the layer the driver
abstracts: PostgreSQL's frontend/backend protocol version 3, from first
principles —

- **Framing**: 1-byte message type + int32 length (length includes
  itself); the StartupMessage alone omits the type byte.
- **Handshake**: StartupMessage (protocol 196608, ``user``/``database``
  params) → AuthenticationOk or AuthenticationMD5Password (4-byte salt;
  response ``md5`` + hex(md5(hex(md5(password+user)) + salt))) →
  ParameterStatus* → BackendKeyData → ReadyForQuery.
- **Simple query cycle** ('Q'): RowDescription ('T', field name + type
  OID + text format) → DataRow* ('D', int32-length-prefixed text cells,
  -1 = NULL) → CommandComplete ('C', e.g. ``SELECT 5``) →
  ReadyForQuery; ErrorResponse ('E') with severity/SQLSTATE/message
  fields on failure.

:class:`PostgresWireServer` serves the dialect over in-memory tables with
a minimal SQL engine (CREATE/DROP TABLE, multi-row INSERT with
``ON CONFLICT`` upsert, SELECT with conjunctive WHERE / ORDER BY / LIMIT,
MIN/MAX/COUNT aggregates) plus real transaction control: BEGIN / COMMIT /
ROLLBACK and **two-phase commit** — ``PREPARE TRANSACTION 'gid'`` /
``COMMIT PREPARED`` / ``ROLLBACK PREPARED`` — the primitive under the
reference's XA exactly-once sink
(``JdbcXaSinkFunction.java``, ``XaFacadeImpl.java``).  Prepared
transactions optionally persist to disk and committed gids are
remembered, so a replayed ``COMMIT PREPARED`` after restore is
idempotent.

:class:`PostgresWireClient` speaks the dialect against ANY v3 server
(including real PostgreSQL, for trust/md5 auth and the statement subset).
:class:`PostgresSource` is the FLIP-27 adapter: numeric-range partitioned
scans (``JdbcNumericBetweenParametersProvider.java:42``) with positioned
readers, so checkpoints resume mid-split.  :class:`PostgresSink` buffers
multi-row INSERTs (``JdbcSink.sink`` / ``JdbcBatchingOutputFormat``
analog) and, in exactly-once mode, stages each checkpoint epoch as a
prepared transaction committed on checkpoint completion
(``JdbcSink.exactlyOnceSink:101`` analog).
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import socket
import socketserver
import struct
import threading
from dataclasses import dataclass, field
from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch, StreamElement
from flink_tpu.connectors.sources import Source, SourceSplit
from flink_tpu.connectors.sinks import Sink

PROTOCOL_V3 = 196608  # 3 << 16

#: type name -> (oid, numpy dtype); OIDs are PostgreSQL's pg_type values
_TYPES = {
    "bool": (16, np.dtype(bool)),
    "int8": (20, np.dtype(np.int64)),
    "int4": (23, np.dtype(np.int32)),
    "text": (25, np.dtype(object)),
    "float4": (700, np.dtype(np.float32)),
    "float8": (701, np.dtype(np.float64)),
}
_TYPE_ALIASES = {
    "boolean": "bool", "bigint": "int8", "int": "int4", "integer": "int4",
    "smallint": "int4", "real": "float4", "double": "float8",
    "double precision": "float8", "varchar": "text", "string": "text",
}
_OID_DTYPE = {oid: dt for oid, dt in _TYPES.values()}
_OID_DTYPE[20] = np.dtype(np.int64)


def _canon_type(name: str) -> str:
    name = re.sub(r"\(.*\)", "", name.strip().lower()).strip()
    return _TYPE_ALIASES.get(name, name)


def md5_password(user: str, password: str, salt: bytes) -> str:
    inner = hashlib.md5((password + user).encode()).hexdigest()
    return "md5" + hashlib.md5(inner.encode() + salt).hexdigest()


# ---------------------------------------------------------------------------
# wire encode/decode
# ---------------------------------------------------------------------------

def _msg(type_byte: bytes, payload: bytes) -> bytes:
    return type_byte + struct.pack(">i", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\0"


def _read_exact(sock, n: int) -> bytes:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise ConnectionError("peer closed")
        buf += chunk
    return buf


def read_message(sock) -> Tuple[bytes, bytes]:
    """One framed backend/frontend message -> (type byte, payload)."""
    t = _read_exact(sock, 1)
    (ln,) = struct.unpack(">i", _read_exact(sock, 4))
    return t, _read_exact(sock, ln - 4)


def _row_description(fields: Sequence[Tuple[str, int]]) -> bytes:
    out = [struct.pack(">h", len(fields))]
    for name, oid in fields:
        out.append(_cstr(name))
        out.append(struct.pack(">ihihih", 0, 0, oid, -1, -1, 0))
    return _msg(b"T", b"".join(out))


def _data_row(cells: Sequence[Optional[str]]) -> bytes:
    out = [struct.pack(">h", len(cells))]
    for c in cells:
        if c is None:
            out.append(struct.pack(">i", -1))
        else:
            b = c.encode()
            out.append(struct.pack(">i", len(b)) + b)
    return _msg(b"D", b"".join(out))


def _error(message: str, sqlstate: str = "42601") -> bytes:
    body = (b"S" + _cstr("ERROR") + b"C" + _cstr(sqlstate)
            + b"M" + _cstr(message) + b"\0")
    return _msg(b"E", body)


def _text_cell(v) -> Optional[str]:
    if v is None:
        return None
    if isinstance(v, (bool, np.bool_)):
        return "t" if v else "f"
    if isinstance(v, (float, np.floating)):
        return repr(float(v))
    return str(v)


def _parse_cell(s: Optional[str], dtype: np.dtype):
    if s is None:
        return None
    if dtype == np.dtype(bool):
        return s in ("t", "true", "1")
    if np.issubdtype(dtype, np.integer):
        return int(s)
    if np.issubdtype(dtype, np.floating):
        return float(s)
    return s


# ---------------------------------------------------------------------------
# minimal SQL engine (server side)
# ---------------------------------------------------------------------------

_LIT = (r"(?:'(?:[^']|'')*'|[+-]?\d+(?:\.\d+)?(?:[eE][+-]?\d+)?"
        r"|[+-]?(?:NaN|Inf(?:inity)?)|NULL|TRUE|FALSE)")


def _parse_literal(tok: str):
    t = tok.strip()
    up = t.upper()
    if up == "NULL":
        return None
    if up == "TRUE":
        return True
    if up == "FALSE":
        return False
    if up in ("NAN", "+NAN", "-NAN"):
        return float("nan")
    if up in ("INF", "INFINITY", "+INF", "+INFINITY"):
        return float("inf")
    if up in ("-INF", "-INFINITY"):
        return float("-inf")
    if t.startswith("'"):
        return t[1:-1].replace("''", "'")
    return float(t) if ("." in t or "e" in t or "E" in t) else int(t)


def _split_statements(sql: str) -> List[str]:
    """Split on top-level ';' only — semicolons inside single-quoted
    literals (with '' escapes) belong to the statement."""
    out, start, i, n = [], 0, 0, len(sql)
    in_str = False
    while i < n:
        c = sql[i]
        if in_str:
            if c == "'":
                if i + 1 < n and sql[i + 1] == "'":
                    i += 1          # escaped quote
                else:
                    in_str = False
        elif c == "'":
            in_str = True
        elif c == ";":
            out.append(sql[start:i])
            start = i + 1
        i += 1
    out.append(sql[start:])
    return [s for s in out if s.strip()]


def _split_tuples(values_sql: str) -> List[str]:
    """Top-level parenthesized tuple bodies of a VALUES list, quote-aware.
    Raises on anything that is not tuples separated by commas — a tuple
    the parser cannot read must be an ERROR, never a silent drop."""
    out, i, n = [], 0, len(values_sql)
    while i < n:
        c = values_sql[i]
        if c.isspace() or c == ",":
            i += 1
            continue
        if c != "(":
            raise ValueError(f"malformed VALUES near: {values_sql[i:i+20]!r}")
        depth, in_str, j = 1, False, i + 1
        while j < n and depth:
            cj = values_sql[j]
            if in_str:
                if cj == "'":
                    if j + 1 < n and values_sql[j + 1] == "'":
                        j += 1
                    else:
                        in_str = False
            elif cj == "'":
                in_str = True
            elif cj == "(":
                depth += 1
            elif cj == ")":
                depth -= 1
            j += 1
        if depth:
            raise ValueError("malformed VALUES: unbalanced parentheses")
        out.append(values_sql[i + 1:j - 1])
        i = j
    if not out:
        raise ValueError("malformed VALUES")
    return out


def _split_tuple_literals(body: str) -> List[str]:
    """Comma-separated literals of ONE tuple; every byte must be consumed
    by a literal (strict — no skipping)."""
    lits, pos, n = [], 0, len(body)
    pat = re.compile(r"\s*(%s)\s*(,|$)" % _LIT, re.I)
    while pos < n or not lits:
        m = pat.match(body, pos)
        if not m:
            raise ValueError(f"unsupported literal near: {body[pos:pos+20]!r}")
        lits.append(m.group(1))
        pos = m.end()
        if m.group(2) != ",":
            break
    if pos < n and body[pos:].strip():
        raise ValueError(f"unsupported literal near: {body[pos:pos+20]!r}")
    return lits


@dataclass
class _Table:
    name: str
    columns: List[str]
    types: List[str]              # canonical type names
    pkey: Optional[str] = None
    rows: Dict[str, list] = field(default_factory=dict)  # col -> values
    pk_index: Dict[Any, int] = field(default_factory=dict)

    def __post_init__(self):
        if not self.rows:
            self.rows = {c: [] for c in self.columns}

    def nrows(self) -> int:
        return len(self.rows[self.columns[0]]) if self.columns else 0

    def insert(self, cols: List[str], values: List[list], on_conflict: str):
        """``on_conflict``: "error" (plain INSERT), "update" (DO UPDATE),
        or "ignore" (DO NOTHING)."""
        missing = [c for c in cols if c not in self.columns]
        if missing:
            raise ValueError(f"column {missing[0]} does not exist")
        for row in values:
            asmap = dict(zip(cols, row))
            if self.pkey is not None and self.pkey in asmap:
                pk = asmap[self.pkey]
                at = self.pk_index.get(pk)
                if at is not None:
                    if on_conflict == "error":
                        raise ValueError(
                            f"duplicate key value violates unique "
                            f"constraint on {self.pkey}")
                    if on_conflict == "update":
                        for c, v in asmap.items():
                            self.rows[c][at] = v
                    continue          # "ignore": row dropped
                self.pk_index[pk] = self.nrows()
            for c in self.columns:
                self.rows[c].append(asmap.get(c))

    def oid_of(self, col: str) -> int:
        return _TYPES[self.types[self.columns.index(col)]][0]

    def dtype_of(self, col: str) -> np.dtype:
        return _TYPES[self.types[self.columns.index(col)]][1]


class _MiniSql:
    """The statement subset the wire server evaluates (enough for the
    connector seams and for foreign clients doing the same shapes)."""

    def __init__(self, server: "PostgresWireServer"):
        self.srv = server

    # each handler returns (command_tag, fields, rows) — fields None for
    # statements that produce no result set
    def execute(self, sql: str, txn: "_TxnState"):
        s = sql.strip().rstrip(";").strip()
        if not s:
            return ("EMPTY", None, None)
        up = s.upper()
        if up == "BEGIN" or up.startswith("BEGIN "):
            txn.explicit = True
            return ("BEGIN", None, None)
        if up == "COMMIT":
            # validate-then-apply, mirroring COMMIT PREPARED: a constraint
            # violation must roll the whole txn back, not leave the rows
            # staged before the offending one committed.  The (reentrant)
            # server lock spans BOTH steps — a concurrent commit applying
            # between validate and apply would re-introduce partial commits
            with self.srv._lock:
                self.srv._validate_staged(txn.staged)
                self.srv._apply_staged(txn.staged)
            txn.reset()
            return ("COMMIT", None, None)
        if up == "ROLLBACK":
            txn.reset()
            return ("ROLLBACK", None, None)
        m = re.match(r"PREPARE\s+TRANSACTION\s+'([^']*)'$", s, re.I)
        if m:
            self.srv._prepare(m.group(1), txn.staged)
            txn.reset()
            return ("PREPARE TRANSACTION", None, None)
        m = re.match(r"COMMIT\s+PREPARED\s+'([^']*)'$", s, re.I)
        if m:
            self.srv._commit_prepared(m.group(1))
            return ("COMMIT PREPARED", None, None)
        m = re.match(r"ROLLBACK\s+PREPARED\s+'([^']*)'$", s, re.I)
        if m:
            self.srv._rollback_prepared(m.group(1))
            return ("ROLLBACK PREPARED", None, None)
        if up.startswith("CREATE TABLE"):
            return self._create(s)
        if up.startswith("DROP TABLE"):
            return self._drop(s)
        if up.startswith("INSERT"):
            return self._insert(s, txn)
        if up.startswith("SELECT"):
            return self._select(s)
        raise ValueError(f"unsupported statement: {s.split()[0]}")

    def _create(self, s: str):
        m = re.match(r"CREATE\s+TABLE\s+(IF\s+NOT\s+EXISTS\s+)?(\w+)\s*\((.*)\)$",
                     s, re.I | re.S)
        if not m:
            raise ValueError("malformed CREATE TABLE")
        if_not, name, body = m.group(1), m.group(2).lower(), m.group(3)
        with self.srv._lock:
            if name in self.srv.tables:
                if if_not:
                    return ("CREATE TABLE", None, None)
                raise ValueError(f"relation {name} already exists")
            cols, types, pkey = [], [], None
            for part in re.split(r",(?![^()]*\))", body):
                part = part.strip()
                pm = re.match(r"(\w+)\s+([\w ]+?)(\s+PRIMARY\s+KEY)?$",
                              part, re.I)
                if not pm:
                    raise ValueError(f"malformed column def: {part}")
                cname = pm.group(1).lower()
                ctype = _canon_type(pm.group(2))
                if ctype not in _TYPES:
                    raise ValueError(f"unknown type {pm.group(2).strip()}")
                cols.append(cname)
                types.append(ctype)
                if pm.group(3):
                    pkey = cname
            self.srv.tables[name] = _Table(name, cols, types, pkey)
        return ("CREATE TABLE", None, None)

    def _drop(self, s: str):
        m = re.match(r"DROP\s+TABLE\s+(IF\s+EXISTS\s+)?(\w+)$", s, re.I)
        if not m:
            raise ValueError("malformed DROP TABLE")
        with self.srv._lock:
            if m.group(2).lower() not in self.srv.tables and not m.group(1):
                raise ValueError(f"relation {m.group(2)} does not exist")
            self.srv.tables.pop(m.group(2).lower(), None)
        return ("DROP TABLE", None, None)

    def _insert(self, s: str, txn: "_TxnState"):
        m = re.match(
            r"INSERT\s+INTO\s+(\w+)\s*\(([^)]*)\)\s*VALUES\s*(.*?)"
            r"(\s+ON\s+CONFLICT\s*(?:\([^)]*\))?\s*DO\s+(UPDATE|NOTHING)"
            r".*)?$",
            s, re.I | re.S)
        if not m:
            raise ValueError("malformed INSERT")
        table = m.group(1).lower()
        cols = [c.strip().lower() for c in m.group(2).split(",")]
        on_conflict = ("error" if m.group(5) is None
                       else ("update" if m.group(5).upper() == "UPDATE"
                             else "ignore"))
        values = []
        for t in _split_tuples(m.group(3)):
            lits = _split_tuple_literals(t)
            if len(lits) != len(cols):
                raise ValueError("INSERT has more/fewer expressions than "
                                 "target columns")
            values.append([_parse_literal(l) for l in lits])
        with self.srv._lock:
            if table not in self.srv.tables:
                raise ValueError(f"relation {table} does not exist")
        op = ("insert", table, cols, values, on_conflict)
        if txn.explicit:
            txn.staged.append(op)
        else:
            self.srv._apply_staged([op])
        return (f"INSERT 0 {len(values)}", None, None)

    def _where_mask(self, t: _Table, clause: Optional[str]) -> np.ndarray:
        n = t.nrows()
        mask = np.ones(n, bool)
        if not clause:
            return mask
        for cond in re.split(r"\s+AND\s+", clause.strip(), flags=re.I):
            cm = re.match(r"(\w+)\s*(=|<>|!=|<=|>=|<|>)\s*(%s)$" % _LIT,
                          cond.strip(), re.I)
            if not cm:
                raise ValueError(f"unsupported WHERE condition: {cond}")
            col, op, lit = cm.group(1).lower(), cm.group(2), \
                _parse_literal(cm.group(3))
            if col not in t.columns:
                raise ValueError(f"column {col} does not exist")
            vals = np.asarray(t.rows[col], dtype=object)
            present = np.asarray([v is not None for v in vals.tolist()], bool)
            cmpv = np.zeros(n, bool)
            if present.any():
                lhs = vals[present]
                try:
                    lhs = lhs.astype(t.dtype_of(col))
                except (TypeError, ValueError):
                    pass
                res = {"=": lhs == lit, "<>": lhs != lit, "!=": lhs != lit,
                       "<": lhs < lit, ">": lhs > lit,
                       "<=": lhs <= lit, ">=": lhs >= lit}[op]
                cmpv[np.flatnonzero(present)] = res
            mask &= cmpv
        return mask

    def _select(self, s: str):
        m = re.match(
            r"SELECT\s+(.*?)\s+FROM\s+(\w+)"
            r"(?:\s+WHERE\s+(.*?))?"
            r"(?:\s+ORDER\s+BY\s+(\w+)(\s+DESC|\s+ASC)?)?"
            r"(?:\s+LIMIT\s+(\d+))?$", s, re.I | re.S)
        if not m:
            raise ValueError("malformed SELECT")
        proj, table, where, order, direction, limit = m.groups()
        with self.srv._lock:
            if table.lower() == "pg_prepared_xacts":
                # the catalog view real PostgreSQL exposes for dangling 2PC
                # txns — materialized as a transient relation so the generic
                # path below evaluates projections/aggregates/WHERE/ORDER/
                # LIMIT on it like any other table
                t = _Table("pg_prepared_xacts", ["gid"], ["text"],
                           rows={"gid": sorted(self.srv.prepared)})
            else:
                t = self.srv.tables.get(table.lower())
            if t is None:
                raise ValueError(f"relation {table} does not exist")
            mask = self._where_mask(t, where)
            idx = np.flatnonzero(mask)
            # aggregates: MIN/MAX/COUNT
            aggs = re.findall(r"(MIN|MAX|COUNT)\s*\(\s*(\*|\w+)\s*\)",
                              proj, re.I)
            if aggs:
                fields, row = [], []
                for fn, col in aggs:
                    fn = fn.upper()
                    if fn == "COUNT":
                        fields.append((f"count", 20))
                        row.append(str(int(idx.size)))
                        continue
                    col = col.lower()
                    vals = [t.rows[col][i] for i in idx.tolist()
                            if t.rows[col][i] is not None]
                    fields.append((fn.lower(), t.oid_of(col)))
                    if not vals:
                        row.append(None)
                    else:
                        row.append(_text_cell(min(vals) if fn == "MIN"
                                              else max(vals)))
                return ("SELECT 1", fields, [row])
            cols = (list(t.columns) if proj.strip() == "*"
                    else [c.strip().lower() for c in proj.split(",")])
            for c in cols:
                if c not in t.columns:
                    raise ValueError(f"column {c} does not exist")
            if order:
                ocol = order.lower()
                if ocol not in t.columns:
                    raise ValueError(f"column {ocol} does not exist")
                key = [t.rows[ocol][i] for i in idx.tolist()]
                # NULLs sort last (PostgreSQL's ASC default); python sort is
                # stable and None-safe via the (is-null, value) key
                srt = sorted(range(len(key)),
                             key=lambda j: (key[j] is None,
                                            key[j] if key[j] is not None
                                            else 0))
                if direction and direction.strip().upper() == "DESC":
                    srt = srt[::-1]
                idx = idx[np.asarray(srt, np.int64)] if srt else idx
            if limit is not None:
                idx = idx[: int(limit)]
            fields = [(c, t.oid_of(c)) for c in cols]
            rows = [[_text_cell(t.rows[c][i]) for c in cols]
                    for i in idx.tolist()]
        return (f"SELECT {len(rows)}", fields, rows)


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


@dataclass
class _TxnState:
    explicit: bool = False
    staged: list = field(default_factory=list)

    def reset(self):
        self.explicit = False
        self.staged = []


class PostgresWireServer:
    """In-process server speaking the v3 dialect (trust, md5, or
    SCRAM-SHA-256 auth; simple AND extended query protocols).

    ``persist_dir`` makes prepared transactions and the committed-gid set
    durable (JSON files), so a 2PC sink's replayed ``COMMIT PREPARED``
    stays idempotent across server restarts."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 users: Optional[Dict[str, str]] = None,
                 persist_dir: Optional[str] = None,
                 auth: str = "md5"):
        if auth not in ("md5", "scram-sha-256"):
            raise ValueError(f"unsupported auth {auth!r}")
        self.auth = auth
        self.users = users  # None = trust everyone
        self.tables: Dict[str, _Table] = {}
        self.prepared: Dict[str, list] = {}
        self.committed_gids: set = set()
        self._lock = threading.RLock()
        self.persist_dir = persist_dir
        if persist_dir:
            os.makedirs(persist_dir, exist_ok=True)
            self._load_persisted()
        self._sql = _MiniSql(self)
        srv_self = self

        class Handler(socketserver.BaseRequestHandler):
            def handle(self):
                try:
                    srv_self._serve_conn(self.request)
                except (ConnectionError, OSError):
                    pass

        class TCP(socketserver.ThreadingTCPServer):
            allow_reuse_address = True
            daemon_threads = True

        self._tcp = TCP((host, port), Handler)
        self.host, self.port = self._tcp.server_address
        self._thread = threading.Thread(target=self._tcp.serve_forever,
                                        daemon=True)
        self._thread.start()

    # -- durability ---------------------------------------------------------
    def _gid_path(self, gid: str) -> str:
        safe = re.sub(r"[^\w.-]", "_", gid)
        return os.path.join(self.persist_dir, f"prepared-{safe}.json")

    def _load_persisted(self):
        cg = os.path.join(self.persist_dir, "committed-gids.json")
        if os.path.exists(cg):
            with open(cg) as f:
                self.committed_gids = set(json.load(f))
        for fn in os.listdir(self.persist_dir):
            if fn.startswith("prepared-") and fn.endswith(".json"):
                with open(os.path.join(self.persist_dir, fn)) as f:
                    rec = json.load(f)
                self.prepared[rec["gid"]] = [tuple(op) for op in rec["ops"]]

    def _persist_committed(self):
        if not self.persist_dir:
            return
        cg = os.path.join(self.persist_dir, "committed-gids.json")
        tmp = cg + ".tmp"
        with open(tmp, "w") as f:
            json.dump(sorted(self.committed_gids), f)
        os.replace(tmp, cg)

    # -- transaction plumbing ----------------------------------------------
    def _apply_staged(self, staged: list) -> None:
        with self._lock:
            for op in staged:
                kind, table, cols, values, on_conflict = op
                t = self.tables.get(table)
                if t is None:
                    raise ValueError(f"relation {table} does not exist")
                t.insert(list(cols), [list(v) for v in values],
                         str(on_conflict))

    def _prepare(self, gid: str, staged: list) -> None:
        with self._lock:
            if gid in self.prepared:
                raise ValueError(f"transaction identifier {gid!r} is "
                                 "already in use")
            self.prepared[gid] = list(staged)
            if self.persist_dir:
                tmp = self._gid_path(gid) + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"gid": gid, "ops": self.prepared[gid]}, f)
                os.replace(tmp, self._gid_path(gid))

    def _validate_staged(self, staged: list) -> None:
        """Every failure ``insert`` could raise, checked BEFORE any row is
        applied — a failed COMMIT PREPARED must leave the txn prepared and
        the tables untouched (atomicity)."""
        pk_seen: Dict[str, set] = {}
        for op in staged:
            _kind, table, cols, values, on_conflict = op
            t = self.tables.get(table)
            if t is None:
                raise ValueError(f"relation {table} does not exist")
            missing = [c for c in cols if c not in t.columns]
            if missing:
                raise ValueError(f"column {missing[0]} does not exist")
            if t.pkey is not None and t.pkey in cols \
                    and on_conflict == "error":
                at = list(cols).index(t.pkey)
                seen = pk_seen.setdefault(table, set(t.pk_index))
                for row in values:
                    if row[at] in seen:
                        raise ValueError(
                            f"duplicate key value violates unique "
                            f"constraint on {t.pkey}")
                    seen.add(row[at])

    def _commit_prepared(self, gid: str) -> None:
        with self._lock:
            if gid in self.committed_gids:
                return  # idempotent replay (2PC restore path)
            staged = self.prepared.get(gid)
            if staged is None:
                raise ValueError(f"prepared transaction with identifier "
                                 f"{gid!r} does not exist")
            self._validate_staged(staged)
            self._apply_staged(staged)
            self.prepared.pop(gid)   # only after a fully successful apply
            self.committed_gids.add(gid)
            self._persist_committed()
            if self.persist_dir:
                try:
                    os.remove(self._gid_path(gid))
                except FileNotFoundError:
                    pass

    def _rollback_prepared(self, gid: str) -> None:
        with self._lock:
            if gid not in self.prepared:
                # real PostgreSQL rejects rollback of an unknown gid — the
                # restore path must enumerate pg_prepared_xacts, not probe
                raise ValueError(f"prepared transaction with identifier "
                                 f"{gid!r} does not exist")
            self.prepared.pop(gid)
            if self.persist_dir:
                try:
                    os.remove(self._gid_path(gid))
                except FileNotFoundError:
                    pass

    def list_prepared(self) -> List[str]:
        with self._lock:
            return sorted(self.prepared)

    # -- connection loop ----------------------------------------------------
    def _serve_conn(self, sock) -> None:
        (ln,) = struct.unpack(">i", _read_exact(sock, 4))
        payload = _read_exact(sock, ln - 4)
        (proto,) = struct.unpack(">i", payload[:4])
        if proto == 80877103:           # SSLRequest: politely decline
            sock.sendall(b"N")
            return self._serve_conn(sock)
        if proto != PROTOCOL_V3:
            sock.sendall(_error(f"unsupported protocol {proto}", "08P01"))
            return
        params: Dict[str, str] = {}
        parts = payload[4:].split(b"\0")
        for k, v in zip(parts[::2], parts[1::2]):
            if k:
                params[k.decode()] = v.decode()
        user = params.get("user", "")
        if self.users is not None:
            if self.auth == "scram-sha-256":
                if not self._scram_handshake(sock, user):
                    return
            else:
                salt = os.urandom(4)
                sock.sendall(_msg(b"R", struct.pack(">i", 5) + salt))
                t, body = read_message(sock)
                if t != b"p":
                    sock.sendall(_error("expected password message",
                                        "28000"))
                    return
                given = body.rstrip(b"\0").decode()
                want = self.users.get(user)
                if want is None or given != md5_password(user, want, salt):
                    sock.sendall(_error(
                        f'password authentication failed for user '
                        f'"{user}"', "28P01"))
                    return
        sock.sendall(_msg(b"R", struct.pack(">i", 0)))          # AuthOk
        self._post_auth(sock)
        txn = _TxnState()
        self._message_loop(sock, txn)

    def _scram_handshake(self, sock, user: str) -> bool:
        """SCRAM-SHA-256 (RFC 5802/7677, the PostgreSQL 10+ default):
        AuthenticationSASL → SASLInitialResponse → SASLContinue →
        client-final-with-proof → SASLFinal.  Mutual: the client proves
        the password via ClientProof, the server proves it KNOWS the
        password via ServerSignature.  Malformed client messages answer
        with an ErrorResponse, never a dropped socket."""
        try:
            return self._scram_handshake_inner(sock, user)
        except (KeyError, ValueError, IndexError, struct.error) as e:
            try:
                sock.sendall(_error(f"malformed SCRAM message: "
                                    f"{e or type(e).__name__}", "28000"))
            except OSError:
                pass
            return False

    def _scram_handshake_inner(self, sock, user: str) -> bool:
        from flink_tpu.security.scram import ScramServer

        sock.sendall(_msg(b"R", struct.pack(">i", 10)
                          + _cstr("SCRAM-SHA-256") + b"\0"))
        t, body = read_message(sock)
        if t != b"p":
            sock.sendall(_error("expected SASLInitialResponse", "28000"))
            return False
        nul = body.index(b"\0")
        mech = body[:nul].decode()
        (ln,) = struct.unpack_from(">i", body, nul + 1)
        client_first = body[nul + 5:nul + 5 + ln].decode()
        if mech != "SCRAM-SHA-256":
            sock.sendall(_error(f"unsupported SASL mechanism {mech}",
                                "28000"))
            return False
        want = self.users.get(user)     # PG: the STARTUP user, not n=
        if want is None:
            sock.sendall(_error(
                f'password authentication failed for user "{user}"',
                "28P01"))
            return False
        scram = ScramServer()           # shared RFC 5802 math (security/)
        server_first = scram.first_response(client_first, want)
        sock.sendall(_msg(b"R", struct.pack(">i", 11)
                          + server_first.encode()))
        t, body = read_message(sock)
        if t != b"p":
            sock.sendall(_error("expected SASLResponse", "28000"))
            return False
        ok, final = scram.verify_final(body.decode())
        if not ok:
            sock.sendall(_error(
                f'password authentication failed for user "{user}"',
                "28P01"))
            return False
        sock.sendall(_msg(b"R", struct.pack(">i", 12) + final.encode()))
        return True

    def _post_auth(self, sock) -> None:
        for k, v in (("server_version", "14.0 (flink-tpu)"),
                     ("client_encoding", "UTF8")):
            sock.sendall(_msg(b"S", _cstr(k) + _cstr(v)))
        sock.sendall(_msg(b"K", struct.pack(">ii", os.getpid() & 0x7FFFFFFF,
                                            12345)))
        sock.sendall(_msg(b"Z", b"I"))

    def _message_loop(self, sock, txn) -> None:
        #: extended-protocol state (Parse/Bind/Describe/Execute/Sync —
        #: the JDBC-driver flow): prepared statements by name and bound
        #: portals (query + lazily cached result, so Describe's
        #: RowDescription and Execute's DataRows come from ONE evaluation)
        stmts: Dict[str, str] = {}
        portals: Dict[str, dict] = {}
        ext_out: List[bytes] = []
        aborted = [False]
        while True:
            t, body = read_message(sock)
            if t == b"X":
                return
            if t == b"Q":
                # a simple Query amid an extended batch acts as an
                # implicit Sync: buffered extended replies flush FIRST
                # (response order must match request order) and the
                # aborted state clears
                if ext_out:
                    sock.sendall(b"".join(ext_out))
                    ext_out.clear()
                aborted[0] = False
                sql = body.rstrip(b"\0").decode()
                out = []
                try:
                    for stmt in _split_statements(sql) or [""]:
                        tag, fields, rows = self._sql.execute(stmt, txn)
                        if tag == "EMPTY":
                            out.append(_msg(b"I", b""))
                            continue
                        if fields is not None:
                            out.append(_row_description(fields))
                            for r in rows:
                                out.append(_data_row(r))
                        out.append(_msg(b"C", _cstr(tag)))
                except (ValueError, TypeError, KeyError, IndexError) as e:
                    # every statement failure must surface as an 'E'
                    # message + ReadyForQuery, never kill the connection
                    out.append(_error(str(e) or type(e).__name__))
                    txn.reset()
                out.append(_msg(b"Z", b"T" if txn.explicit else b"I"))
                sock.sendall(b"".join(out))
            elif t in (b"P", b"B", b"D", b"E", b"C", b"H", b"S"):
                self._extended(t, body, txn, stmts, portals, ext_out,
                               aborted, sock)
            else:
                sock.sendall(_error(f"unsupported message {t!r}", "08P01"))
                sock.sendall(_msg(b"Z", b"I"))

    def _extended(self, t: bytes, body: bytes, txn, stmts, portals,
                  out: List[bytes], aborted: List[bool], sock) -> None:
        """One extended-protocol message.  Responses buffer until Sync or
        Flush; an error puts the connection in the aborted state, where
        everything but Sync is skipped (the reference's
        skip-till-sync)."""
        if t == b"S":                        # Sync: flush + ReadyForQuery
            out.append(_msg(b"Z", b"T" if txn.explicit else b"I"))
            sock.sendall(b"".join(out))
            out.clear()
            aborted[0] = False
            return
        if t == b"H":                        # Flush
            sock.sendall(b"".join(out))
            out.clear()
            return
        if aborted[0]:
            return
        try:
            if t == b"P":                    # Parse
                nul1 = body.index(b"\0")
                name = body[:nul1].decode()
                nul2 = body.index(b"\0", nul1 + 1)
                stmts[name] = body[nul1 + 1:nul2].decode()
                out.append(_msg(b"1", b""))
            elif t == b"B":                  # Bind
                pos = body.index(b"\0")
                portal = body[:pos].decode()
                pos += 1
                end = body.index(b"\0", pos)
                stmt_name = body[pos:end].decode()
                pos = end + 1
                (nfmt,) = struct.unpack_from(">h", body, pos)
                pos += 2
                fmts = struct.unpack_from(f">{nfmt}h", body, pos) \
                    if nfmt else ()
                pos += 2 * nfmt
                if any(f == 1 for f in fmts):
                    # binary-format parameters would be misread as UTF-8
                    # text: reject explicitly rather than corrupt
                    raise ValueError("binary-format parameters are not "
                                     "supported (send text format)")
                (nparams,) = struct.unpack_from(">h", body, pos)
                pos += 2
                params: List[Optional[str]] = []
                for _ in range(nparams):
                    (ln,) = struct.unpack_from(">i", body, pos)
                    pos += 4
                    if ln < 0:
                        params.append(None)
                    else:
                        params.append(body[pos:pos + ln].decode())
                        pos += ln
                (nrfmt,) = struct.unpack_from(">h", body, pos)
                rfmts = struct.unpack_from(f">{nrfmt}h", body, pos + 2) \
                    if nrfmt else ()
                if any(f == 1 for f in rfmts):
                    raise ValueError("binary result format is not "
                                     "supported (request text format)")
                if stmt_name not in stmts:
                    raise ValueError(f"unknown prepared statement "
                                     f"{stmt_name!r}")
                portals[portal] = {
                    "query": _substitute_params(stmts[stmt_name], params)}
                out.append(_msg(b"2", b""))
            elif t == b"D":                  # Describe
                kind, name = chr(body[0]), body[1:].rstrip(b"\0").decode()
                if kind == "P":
                    p = portals.get(name)
                    if p is None:
                        raise ValueError(f"unknown portal {name!r}")
                    self._run_portal(p, txn)
                    out.append(_row_description(p["fields"])
                               if p["fields"] is not None
                               else _msg(b"n", b""))
                else:                        # statement: no param typing
                    out.append(_msg(b"n", b""))
            elif t == b"E":                  # Execute
                name = body[:body.index(b"\0")].decode()
                p = portals.get(name)
                if p is None:
                    raise ValueError(f"unknown portal {name!r}")
                self._run_portal(p, txn)
                if p["tag"] == "EMPTY":
                    out.append(_msg(b"I", b""))
                else:
                    for r in (p["rows"] or []):
                        out.append(_data_row(r))
                    out.append(_msg(b"C", _cstr(p["tag"])))
            elif t == b"C":                  # Close statement/portal
                kind, name = chr(body[0]), body[1:].rstrip(b"\0").decode()
                (stmts if kind == "S" else portals).pop(name, None)
                out.append(_msg(b"3", b""))
        except (ValueError, TypeError, KeyError, IndexError,
                struct.error) as e:
            out.append(_error(str(e) or type(e).__name__))
            aborted[0] = True
            txn.reset()

    def _run_portal(self, p: dict, txn) -> None:
        """Evaluate the portal's query ONCE; Describe and Execute share
        the result (the reference derives Describe metadata without
        executing; the mini engine evaluates eagerly instead)."""
        if "tag" not in p:
            tag, fields, rows = self._sql.execute(p["query"], txn)
            p["tag"], p["fields"], p["rows"] = tag, fields, rows

    def close(self):
        self._tcp.shutdown()
        self._tcp.server_close()


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class PostgresError(Exception):
    def __init__(self, fields: Dict[str, str]):
        self.fields = fields
        super().__init__(fields.get("M", "postgres error"))


class PostgresWireClient:
    """Minimal v3 frontend: startup + trust/md5 auth + simple query."""

    def __init__(self, host: str, port: int, user: str = "flink",
                 password: str = "", database: str = "flink",
                 timeout: float = 10.0):
        self.sock = socket.create_connection((host, port), timeout=timeout)
        try:
            payload = struct.pack(">i", PROTOCOL_V3) + _cstr("user") \
                + _cstr(user) + _cstr("database") + _cstr(database) + b"\0"
            self.sock.sendall(struct.pack(">i", len(payload) + 4) + payload)
            self.parameters: Dict[str, str] = {}
            scram: Dict[str, Any] = {}
            while True:
                t, body = read_message(self.sock)
                if t == b"R":
                    (code,) = struct.unpack(">i", body[:4])
                    if code == 0:
                        continue
                    if code == 5:
                        pw = md5_password(user, password, body[4:8])
                        self.sock.sendall(_msg(b"p", _cstr(pw)))
                        continue
                    if code in (10, 11, 12):
                        self._scram_step(code, body[4:], user, password,
                                         scram)
                        continue
                    raise PostgresError(
                        {"M": f"unsupported auth code {code}"})
                if t == b"S":
                    k, v = body.split(b"\0")[:2]
                    self.parameters[k.decode()] = v.decode()
                elif t == b"E":
                    raise PostgresError(self._error_fields(body))
                elif t == b"Z":
                    return
                # 'K' BackendKeyData and anything else: informational
        except BaseException:
            # a failed handshake (auth rejection, protocol error) must not
            # leak the connected socket — repeated failed connects would
            # accumulate open FDs
            self.sock.close()
            raise

    def _scram_step(self, code: int, payload: bytes, user: str,
                    password: str, st: Dict[str, Any]) -> None:
        """Client half of SCRAM-SHA-256 over the PG SASL framing (auth
        codes 10/11/12), delegating the RFC 5802 math to the shared
        ``flink_tpu.security.scram`` implementation.  Mutual: the final
        step verifies the SERVER's signature."""
        from flink_tpu.security.scram import ScramClient

        if code == 10:                       # AuthenticationSASL
            mechs = [m.decode() for m in payload.split(b"\0") if m]
            if "SCRAM-SHA-256" not in mechs:
                raise PostgresError({"M": f"no usable SASL mechanism "
                                          f"in {mechs}"})
            # PG convention: the SCRAM username is empty (the startup
            # packet already named the user)
            st["scram"] = sc = ScramClient("", password)
            first = sc.first()
            self.sock.sendall(_msg(
                b"p", _cstr("SCRAM-SHA-256")
                + struct.pack(">i", len(first)) + first.encode()))
        elif code == 11:                     # SASLContinue (server-first)
            try:
                final = st["scram"].final(payload.decode())
            except ValueError as e:
                raise PostgresError({"M": str(e)}) from e
            self.sock.sendall(_msg(b"p", final.encode()))
        else:                                # SASLFinal: verify the server
            try:
                st["scram"].verify(payload.decode())
            except ValueError as e:
                raise PostgresError({"M": str(e)}) from e

    @staticmethod
    def _error_fields(body: bytes) -> Dict[str, str]:
        out = {}
        for part in body.split(b"\0"):
            if part:
                out[chr(part[0])] = part[1:].decode()
        return out

    def query(self, sql: str
              ) -> Tuple[List[Tuple[str, int]], List[List[Optional[str]]]]:
        """Simple-query cycle: returns (fields as (name, oid), text rows).
        Statements without a result set return ([], [])."""
        self.sock.sendall(_msg(b"Q", _cstr(sql)))
        return self._read_until_ready()

    def _read_until_ready(self) -> Tuple[List[Tuple[str, int]],
                                         List[List[Optional[str]]]]:
        """Drain responses to ReadyForQuery — shared by the simple AND
        extended query cycles (extended-only messages like ParseComplete
        fall through like CommandComplete does)."""
        fields: List[Tuple[str, int]] = []
        rows: List[List[Optional[str]]] = []
        err: Optional[Dict[str, str]] = None
        while True:
            t, body = read_message(self.sock)
            if t == b"T":
                (n,) = struct.unpack(">h", body[:2])
                off = 2
                fields = []
                rows = []   # a new result set replaces any earlier one
                for _ in range(n):
                    end = body.index(b"\0", off)
                    name = body[off:end].decode()
                    off = end + 1
                    (_tab, _att, oid, _tl, _tm, _fmt) = struct.unpack(
                        ">ihihih", body[off:off + 18])
                    off += 18
                    fields.append((name, oid))
            elif t == b"D":
                (n,) = struct.unpack(">h", body[:2])
                off = 2
                row: List[Optional[str]] = []
                for _ in range(n):
                    (ln,) = struct.unpack(">i", body[off:off + 4])
                    off += 4
                    if ln == -1:
                        row.append(None)
                    else:
                        row.append(body[off:off + ln].decode())
                        off += ln
                rows.append(row)
            elif t == b"E":
                err = self._error_fields(body)
            elif t == b"Z":
                if err is not None:
                    raise PostgresError(err)
                return fields, rows
            # 'C' CommandComplete / 'I' Empty / 'N' Notice / '1' Parse-
            # Complete / '2' BindComplete / 'n' NoData / '3' Close-
            # Complete: fall through

    @staticmethod
    def _typed_columns(fields, rows) -> Dict[str, np.ndarray]:
        out: Dict[str, np.ndarray] = {}
        for j, (name, oid) in enumerate(fields):
            dt = _OID_DTYPE.get(oid, np.dtype(object))
            vals = [_parse_cell(r[j], dt) for r in rows]
            if any(v is None for v in vals):
                dt = np.dtype(object)
            out[name] = np.asarray(vals, dtype=dt)
        return out

    def query_columns(self, sql: str) -> Dict[str, np.ndarray]:
        """Typed columns (numpy, dtype from the field OIDs)."""
        return self._typed_columns(*self.query(sql))

    def execute(self, sql: str) -> None:
        self.query(sql)

    def execute_prepared(self, sql: str, params: Sequence[Any] = ()
                         ) -> Tuple[List[Tuple[str, int]],
                                    List[List[Optional[str]]]]:
        """EXTENDED-protocol cycle (the JDBC PreparedStatement flow):
        Parse → Bind (text-format ``$n`` parameters) → Describe(portal) →
        Execute → Sync; returns (fields, text rows)."""
        def enc(v: Any) -> Optional[bytes]:
            if v is None:
                return None
            if isinstance(v, (bool, np.bool_)):
                return b"true" if v else b"false"
            return str(v).encode()

        parse = _cstr("") + _cstr(sql) + struct.pack(">h", 0)
        bind = bytearray(_cstr("") + _cstr("") + struct.pack(">h", 0))
        bind += struct.pack(">h", len(params))
        for v in params:
            b = enc(v)
            if b is None:
                bind += struct.pack(">i", -1)
            else:
                bind += struct.pack(">i", len(b)) + b
        bind += struct.pack(">h", 0)
        frames = (_msg(b"P", parse) + _msg(b"B", bytes(bind))
                  + _msg(b"D", b"P\0") + _msg(b"E", _cstr("")
                                              + struct.pack(">i", 0))
                  + _msg(b"S", b""))
        self.sock.sendall(frames)
        return self._read_until_ready()

    def query_prepared(self, sql: str, params: Sequence[Any] = ()
                       ) -> Dict[str, np.ndarray]:
        """Typed columns via the extended protocol (``query_columns``'s
        prepared-statement twin)."""
        return self._typed_columns(*self.execute_prepared(sql, params))

    def close(self):
        try:
            self.sock.sendall(_msg(b"X", b""))
        except OSError:
            pass
        self.sock.close()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# connector seams
# ---------------------------------------------------------------------------


_NUM_INT = re.compile(r"[+-]?\d+$")
_NUM_FLOAT = re.compile(r"[+-]?(\d+\.\d*|\.\d+|\d+)([eE][+-]?\d+)?$")


def _substitute_params(query: str, params: List[Optional[str]]) -> str:
    """Extended-protocol Bind: inline text-format parameters for ``$n``
    placeholders OUTSIDE string literals (the mini engine evaluates SQL
    text; a full server binds into a parse tree).  Values quote as typed
    literals — strictly numeric text stays bare (``1_0``/``infinity``
    spellings that Python's int()/float() accept do NOT count), anything
    else single-quotes."""
    def lit(v: Optional[str]) -> str:
        if v is None:
            return "NULL"
        if _NUM_INT.fullmatch(v) or _NUM_FLOAT.fullmatch(v):
            return v
        if v.lower() in ("true", "false"):
            return v
        return "'" + v.replace("'", "''") + "'"

    out: List[str] = []
    i, n = 0, len(query)
    in_str = False
    while i < n:
        ch = query[i]
        if ch == "'":
            in_str = not in_str
            out.append(ch)
            i += 1
        elif ch == "$" and not in_str and i + 1 < n \
                and query[i + 1].isdigit():
            j = i + 1
            while j < n and query[j].isdigit():
                j += 1
            idx = int(query[i + 1:j]) - 1
            if not 0 <= idx < len(params):
                raise ValueError(f"parameter ${query[i + 1:j]} not bound")
            out.append(lit(params[idx]))
            i = j
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _sql_literal(v) -> str:
    if v is None:
        return "NULL"
    if isinstance(v, (bool, np.bool_)):
        return "TRUE" if v else "FALSE"
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    if isinstance(v, (float, np.floating)):
        return repr(float(v))
    return "'" + str(v).replace("'", "''") + "'"


@dataclass
class PostgresSplit(SourceSplit):
    lo: Any = None                     # inclusive lower bound
    hi: Any = None                     # upper bound on partition_column
    hi_inclusive: bool = True          # last split closes the range

    def split_id(self) -> str:
        return f"pg:{self.lo}..{self.hi}{'i' if self.hi_inclusive else ''}"

    def read(self) -> Iterator[StreamElement]:
        return self.source.open_split(self, None)


class _PositionedPgReader:
    """Iterator over one split's batches; ``position`` = rows already
    emitted within the split's ordered range scan, so checkpoints resume
    mid-split (the repo-wide positioned-reader seam, file_source.py)."""

    def __init__(self, source: "PostgresSource", split: PostgresSplit,
                 start_row: int):
        self.position = int(start_row)
        self._it = source._read_range(split.lo, split.hi,
                                      split.hi_inclusive, self.position)

    def __iter__(self):
        return self

    def __next__(self) -> StreamElement:
        el = next(self._it)
        if isinstance(el, RecordBatch):
            self.position += len(el)
        return el


class PostgresSource(Source):
    """Bounded partitioned table scan (``JdbcRowDataInputFormat`` +
    ``JdbcNumericBetweenParametersProvider.java:42`` analog): splits are
    equal numeric ranges of ``partition_column`` between its MIN and MAX,
    each read as an ordered range SELECT."""

    def __init__(self, host: str, port: int, table: str,
                 partition_column: str, columns: Optional[List[str]] = None,
                 num_splits: int = 0, batch_size: int = 4096,
                 user: str = "flink", password: str = "",
                 timestamp_column: Optional[str] = None):
        self.host, self.port = host, port
        self.table = table
        self.partition_column = partition_column
        self.columns = columns
        self.num_splits = num_splits
        self.batch_size = batch_size
        self.user, self.password = user, password
        self.timestamp_column = timestamp_column

    def _connect(self) -> PostgresWireClient:
        return PostgresWireClient(self.host, self.port, user=self.user,
                                  password=self.password)

    def create_splits(self, parallelism: int) -> List[PostgresSplit]:
        n = self.num_splits or parallelism
        with self._connect() as c:
            cols = c.query_columns(
                f"SELECT MIN({self.partition_column}), "
                f"MAX({self.partition_column}), COUNT(*) FROM {self.table}")
        if int(cols["count"][0]) == 0 or cols["min"][0] is None:
            return []
        lo_v, hi_v = cols["min"][0], cols["max"][0]
        n = max(1, n)
        if hi_v <= lo_v:
            return [PostgresSplit(self, 0, 1, lo=lo_v, hi=hi_v,
                                  hi_inclusive=True)]
        if isinstance(lo_v, (int, np.integer)) \
                and isinstance(hi_v, (int, np.integer)):
            # exact integer arithmetic (JdbcNumericBetweenParametersProvider):
            # float() rounding of int8 values beyond 2^53 can push the lower
            # bound above the true MIN and drop boundary rows from every split
            # (Python ints: np.int64 would overflow on span * i)
            lo_i, hi_i = int(lo_v), int(hi_v)
            span = hi_i - lo_i + 1
            bounds = [lo_i + span * i // n for i in range(n)] + [hi_i]
        else:
            lo, hi = float(lo_v), float(hi_v)
            # HALF-OPEN real-valued boundaries [b_i, b_{i+1}) and a closed
            # last split — integer-rounded inclusive ranges would silently
            # drop fractional values falling between splits
            bounds = [lo + (hi - lo) * i / n for i in range(n)] + [hi]
        splits = []
        for i in range(n):
            splits.append(PostgresSplit(
                self, i, n, lo=bounds[i], hi=bounds[i + 1],
                hi_inclusive=(i == n - 1)))
        return splits

    def open_split(self, split: PostgresSplit,
                   position: Optional[int]) -> _PositionedPgReader:
        return _PositionedPgReader(self, split, position or 0)

    def _read_range(self, lo, hi, hi_inclusive: bool,
                    skip: int) -> Iterator[StreamElement]:
        proj = ", ".join(self.columns) if self.columns else "*"
        hi_op = "<=" if hi_inclusive else "<"
        with self._connect() as c:
            cols = c.query_columns(
                f"SELECT {proj} FROM {self.table} "
                f"WHERE {self.partition_column} >= {_sql_literal(lo)} "
                f"AND {self.partition_column} {hi_op} {_sql_literal(hi)} "
                f"ORDER BY {self.partition_column}")
        n = 0
        for v in cols.values():
            n = len(v)
            break
        for start in range(skip, n, self.batch_size):
            stop = min(start + self.batch_size, n)
            chunk = {k: v[start:stop] for k, v in cols.items()}
            ts = (np.asarray(chunk[self.timestamp_column], np.int64)
                  if self.timestamp_column else None)
            yield RecordBatch(chunk, timestamps=ts)


class PostgresLookupFunction:
    """Dimension point-lookup against a PostgreSQL server over the wire —
    the ``JdbcRowDataLookupFunction`` analog feeding the SQL layer's
    ``LookupJoinOperator`` (register via
    ``TableEnvironment.register_lookup_table(name, fn, columns,
    key_column)``).  One connection, lazily opened, re-opened on error;
    caching lives in the operator, not here."""

    def __init__(self, host: str, port: int, table: str, key_column: str,
                 columns: Optional[List[str]] = None,
                 user: str = "flink", password: str = ""):
        self.host, self.port = host, port
        self.table = table
        self.key_column = key_column
        self.columns = columns
        self.user, self.password = user, password
        self._conn: Optional[PostgresWireClient] = None

    def _client(self) -> PostgresWireClient:
        if self._conn is None:
            self._conn = PostgresWireClient(self.host, self.port,
                                            user=self.user,
                                            password=self.password)
        return self._conn

    def __call__(self, key) -> List[dict]:
        proj = ", ".join(self.columns) if self.columns else "*"
        sql = (f"SELECT {proj} FROM {self.table} "
               f"WHERE {self.key_column} = {_sql_literal(key)}")
        try:
            cols = self._client().query_columns(sql)
        except (OSError, PostgresError):
            # dropped connection: one reconnect-and-retry
            self.close()
            cols = self._client().query_columns(sql)
        names = list(cols)
        n = len(cols[names[0]]) if names else 0
        return [{c: cols[c][i] for c in names} for i in range(n)]

    def close(self) -> None:
        if self._conn is not None:
            try:
                self._conn.close()
            except OSError:
                pass
            self._conn = None


class PostgresSink(Sink):
    """Buffered relational sink (``JdbcSink.sink`` /
    ``JdbcBatchingOutputFormat`` analog).

    - ``exactly_once=False``: multi-row INSERTs flushed by buffer size and
      on checkpoint (``snapshot_state`` flushes; at-least-once, and
      idempotent end-to-end when ``upsert`` targets a primary key — the
      reference ships the same two delivery shapes).
    - ``exactly_once=True``: rows buffer into an explicit transaction,
      ``snapshot_state`` flushes and stages it as ``PREPARE TRANSACTION``
      bound to the checkpoint epoch; ``notify_checkpoint_complete``
      issues ``COMMIT PREPARED`` (idempotent server-side), and restore
      re-commits the snapshot's staged gids then rolls back any other
      dangling gid of this sink — the XA pattern of
      ``JdbcXaSinkFunction.java`` on PostgreSQL-native 2PC.
    """

    #: each parallel subtask needs its OWN buffers and txn identity
    clone_per_subtask = True

    def __init__(self, host: str, port: int, table: str,
                 columns: List[str], upsert: bool = False,
                 conflict_column: Optional[str] = None,
                 exactly_once: bool = False, buffer_rows: int = 4096,
                 user: str = "flink", password: str = "",
                 sink_id: str = "pg-sink"):
        self.host, self.port = host, port
        self.table = table
        self.columns = list(columns)
        self.upsert = upsert
        #: upsert conflict target (the table's primary key); defaults to
        #: the first sink column
        self.conflict_column = conflict_column or self.columns[0]
        self.exactly_once = exactly_once
        self.buffer_rows = buffer_rows
        self.user, self.password = user, password
        self.sink_id = sink_id
        self._subtask_index = 0
        self._buf: List[list] = []
        self._conn: Optional[PostgresWireClient] = None
        self._epoch = 0               # staged-transaction counter
        #: gids prepared but not yet committed, each bound to the
        #: checkpoint id whose snapshot staged it (None = runtime gave no
        #: id; the legacy notify-before-next-barrier ordering applies)
        self._staged: List[Tuple[str, Optional[int]]] = []
        self._in_txn = False

    def on_cloned(self) -> None:
        self._conn = None             # never share a socket across subtasks

    def open(self, ctx) -> None:
        self._subtask_index = getattr(ctx, "subtask_index", 0)

    # -- plumbing -----------------------------------------------------------
    def _client(self) -> PostgresWireClient:
        if self._conn is None:
            self._conn = PostgresWireClient(self.host, self.port,
                                            user=self.user,
                                            password=self.password)
        return self._conn

    def _gid(self, epoch: int) -> str:
        return f"{self.sink_id}-s{self._subtask_index}-{epoch}"

    def _insert_sql(self, rows: List[list]) -> str:
        vals = ", ".join(
            "(" + ", ".join(_sql_literal(v) for v in row) + ")"
            for row in rows)
        sql = (f"INSERT INTO {self.table} ({', '.join(self.columns)}) "
               f"VALUES {vals}")
        if self.upsert:
            # the full PostgreSQL form — valid against real servers too
            sets = ", ".join(f"{c} = EXCLUDED.{c}" for c in self.columns
                             if c != self.conflict_column)
            sql += (f" ON CONFLICT ({self.conflict_column}) DO UPDATE "
                    f"SET {sets}")
        return sql

    def _flush_buffer(self) -> None:
        if not self._buf:
            return
        c = self._client()
        if self.exactly_once and not self._in_txn:
            c.execute("BEGIN")
            self._in_txn = True
        for lo in range(0, len(self._buf), self.buffer_rows):
            c.execute(self._insert_sql(self._buf[lo:lo + self.buffer_rows]))
        self._buf = []

    # -- Sink contract ------------------------------------------------------
    def write_batch(self, batch: RecordBatch) -> None:
        cols = [np.asarray(batch.column(c)) for c in self.columns]
        for i in range(len(batch)):
            self._buf.append([c[i] for c in cols])
        if not self.exactly_once and len(self._buf) >= self.buffer_rows:
            self._flush_buffer()

    def flush(self) -> None:
        """End-of-input: at-least-once flushes the buffer; exactly-once
        stages and commits the final epoch (input is exhausted — there is
        no later checkpoint left to bind it to)."""
        self._flush_buffer()
        if self.exactly_once and self._in_txn:
            gid = self._gid(self._epoch)
            c = self._client()
            c.execute(f"PREPARE TRANSACTION '{gid}'")
            c.execute(f"COMMIT PREPARED '{gid}'")
            self._in_txn = False
            self._epoch += 1

    def snapshot_state(self) -> Dict[str, Any]:
        from flink_tpu.operators.base import current_checkpoint_id

        self._flush_buffer()
        if self.exactly_once and self._in_txn:
            gid = self._gid(self._epoch)
            self._client().execute(f"PREPARE TRANSACTION '{gid}'")
            self._staged.append((gid, current_checkpoint_id()))
            self._in_txn = False
            self._epoch += 1
        return {"epoch": self._epoch, "staged": list(self._staged)}

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        """Commit ONLY gids staged for checkpoints <= the notified one
        (TwoPhaseCommitSinkFunction contract, same as log_service.py):
        if checkpoints ever pipeline, an epoch staged for a later,
        uncompleted checkpoint must not commit early — a restore to this
        checkpoint would replay its rows and duplicate them."""
        if not self.exactly_once:
            return
        c = self._client()
        keep = []
        for gid, staged_for in self._staged:
            if staged_for is not None and staged_for > checkpoint_id:
                keep.append((gid, staged_for))
                continue
            c.execute(f"COMMIT PREPARED '{gid}'")
        self._staged = keep

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._epoch = int(snap.get("epoch", 0))
        self._buf = []
        self._in_txn = False
        c = self._client()
        # commit the snapshot's staged epochs (their rows are part of the
        # restored checkpoint; COMMIT PREPARED replays idempotently), then
        # abort every OTHER dangling prepared txn of this sink — epochs
        # staged after the restored checkpoint must not surface later.
        # The dangling set is enumerated from pg_prepared_xacts (a real-PG
        # catalog view), not probed by gid range: a restore arbitrarily far
        # behind the crash still finds every orphan, and ROLLBACK PREPARED
        # is only ever issued for gids that actually exist
        committed = set()
        for entry in snap.get("staged", []):
            gid = entry[0] if isinstance(entry, (tuple, list)) else entry
            c.execute(f"COMMIT PREPARED '{gid}'")
            committed.add(gid)
        self._staged = []
        mine = f"{self.sink_id}-s{self._subtask_index}-"
        dangling = c.query_columns("SELECT gid FROM pg_prepared_xacts")
        for gid in dangling.get("gid", []):
            if gid is None or not gid.startswith(mine) or gid in committed:
                continue
            try:
                c.execute(f"ROLLBACK PREPARED '{gid}'")
            except PostgresError:
                pass  # raced with another recovering instance: already gone

    def close(self) -> None:
        if self.exactly_once and self._in_txn and self._conn is not None:
            try:
                self._conn.execute("ROLLBACK")
            except (PostgresError, OSError):
                pass
            self._in_txn = False
        elif not self.exactly_once:
            try:
                self._flush_buffer()
            except (PostgresError, OSError):
                pass
        if self._conn is not None:
            self._conn.close()
            self._conn = None
