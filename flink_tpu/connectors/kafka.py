"""Kafka binary wire protocol: client + broker speaking the real dialect.

The reference's Kafka connector (``flink-connectors/flink-connector-kafka/
.../KafkaSource.java``) talks to brokers over Kafka's binary TCP protocol.
This module implements that protocol from first principles — the v0/v1 API
generation (the long-stable dialect every Kafka client library still
speaks for bootstrapping):

- **Framing**: int32 size prefix; request header ``api_key:int16,
  api_version:int16, correlation_id:int32, client_id:nullable-string``;
  response header ``correlation_id:int32``.
- **APIs**: ApiVersions(18) v0, Metadata(3) v0, Produce(0) v0,
  Fetch(1) v0, ListOffsets(2) v0.
- **Message set v0**: ``[offset:int64 size:int32 message]*`` with
  ``message = crc:uint32 magic:int8(0) attributes:int8 key:bytes
  value:bytes`` — CRC32 over magic..value, verified on both sides.

:class:`KafkaWireBroker` serves the dialect over per-partition in-memory
logs with optional directory persistence; :class:`KafkaWireClient`
produces/fetches against ANY broker speaking v0 (including real Kafka).
:class:`KafkaWireSource`/:class:`KafkaWireSink` adapt them to the
framework's source/sink seams.
"""

from __future__ import annotations

import json as _json
import os
import socket
import struct
import threading
import time
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.connectors.sinks import TwoPhaseCommitSink

# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------


class _Writer:
    def __init__(self):
        self._parts: List[bytes] = []

    def int8(self, v):
        self._parts.append(struct.pack(">b", v))
        return self

    def int16(self, v):
        self._parts.append(struct.pack(">h", v))
        return self

    def int32(self, v):
        self._parts.append(struct.pack(">i", v))
        return self

    def int64(self, v):
        self._parts.append(struct.pack(">q", v))
        return self

    def uint32(self, v):
        self._parts.append(struct.pack(">I", v))
        return self

    def string(self, s: Optional[str]):
        if s is None:
            return self.int16(-1)
        b = s.encode()
        self.int16(len(b))
        self._parts.append(b)
        return self

    def bytes_(self, b: Optional[bytes]):
        if b is None:
            return self.int32(-1)
        self.int32(len(b))
        self._parts.append(b)
        return self

    def raw(self, b: bytes):
        self._parts.append(b)
        return self

    def array(self, items, fn):
        self.int32(len(items))
        for it in items:
            fn(self, it)
        return self

    def done(self) -> bytes:
        return b"".join(self._parts)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        b = self.data[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("short kafka frame")
        self.pos += n
        return b

    def int8(self):
        return struct.unpack(">b", self._take(1))[0]

    def int16(self):
        return struct.unpack(">h", self._take(2))[0]

    def int32(self):
        return struct.unpack(">i", self._take(4))[0]

    def int64(self):
        return struct.unpack(">q", self._take(8))[0]

    def uint32(self):
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> Optional[str]:
        n = self.int16()
        return None if n < 0 else self._take(n).decode()

    def bytes_(self) -> Optional[bytes]:
        n = self.int32()
        return None if n < 0 else self._take(n)

    def array(self, fn) -> list:
        return [fn(self) for _ in range(self.int32())]


# ---------------------------------------------------------------------------
# message set v0
# ---------------------------------------------------------------------------

def encode_message_v0(key: Optional[bytes], value: Optional[bytes]) -> bytes:
    body = (_Writer().int8(0).int8(0)        # magic=0, attributes=0
            .bytes_(key).bytes_(value).done())
    return _Writer().uint32(zlib.crc32(body) & 0xFFFFFFFF).raw(body).done()


def encode_message_set(entries: List[Tuple[int, Optional[bytes],
                                           Optional[bytes]]]) -> bytes:
    w = _Writer()
    for offset, key, value in entries:
        msg = encode_message_v0(key, value)
        w.int64(offset).int32(len(msg)).raw(msg)
    return w.done()


def decode_message_set(data: bytes) -> List[Tuple[int, Optional[bytes],
                                                  Optional[bytes]]]:
    """[(offset, key, value)] — CRC-verified; a trailing partial message
    (the protocol allows brokers to cut a fetch mid-message) is skipped."""
    out = []
    r = _Reader(data)
    while len(data) - r.pos >= 12:
        offset = r.int64()
        size = r.int32()
        if len(data) - r.pos < size:
            break                               # partial trailing message
        msg = r._take(size)
        mr = _Reader(msg)
        crc = mr.uint32()
        body = msg[4:]
        if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
            raise ValueError(f"kafka message CRC mismatch at offset {offset}")
        magic = mr.int8()
        if magic != 0:
            raise ValueError(f"unsupported message magic {magic}")
        mr.int8()                               # attributes (no compression)
        key = mr.bytes_()
        value = mr.bytes_()
        out.append((offset, key, value))
    return out


# error codes (the real protocol's)
_ERR_NONE = 0
_ERR_OFFSET_OUT_OF_RANGE = 1
_ERR_UNKNOWN_TOPIC = 3
_ERR_ILLEGAL_GENERATION = 22
_ERR_UNKNOWN_MEMBER_ID = 25
_ERR_REBALANCE_IN_PROGRESS = 27
_ERR_UNSUPPORTED_SASL_MECHANISM = 33
_ERR_ILLEGAL_SASL_STATE = 34
_ERR_SASL_AUTHENTICATION_FAILED = 58
_ERR_INVALID_PRODUCER_EPOCH = 47
_ERR_INVALID_TXN_STATE = 48
_ERR_FETCH_SESSION_ID_NOT_FOUND = 70
_ERR_INVALID_FETCH_SESSION_EPOCH = 71
_ERR_UNKNOWN = -1

_API_SASL_HANDSHAKE = 17
_API_SASL_AUTHENTICATE = 36


class KafkaError(Exception):
    """Broker-reported protocol error (auth failures, fatal responses).
    ``code`` carries the wire error code when the raiser knows it, so
    recovery paths can distinguish benign replies (e.g. an EndTxn commit
    replay answered INVALID_TXN_STATE because the tid aged out of the
    committed-tids retention) from real failures."""

    def __init__(self, message: str, code: Optional[int] = None):
        super().__init__(message)
        self.code = code

_API_PRODUCE, _API_FETCH, _API_LIST_OFFSETS = 0, 1, 2
_API_METADATA, _API_VERSIONS = 3, 18
_API_OFFSET_COMMIT, _API_OFFSET_FETCH = 8, 9
_API_FIND_COORDINATOR, _API_JOIN_GROUP = 10, 11
_API_HEARTBEAT, _API_LEAVE_GROUP, _API_SYNC_GROUP = 12, 13, 14
_API_INIT_PRODUCER_ID = 22
_API_ADD_PARTITIONS_TO_TXN = 24
_API_END_TXN = 26
_API_LIST_TRANSACTIONS = 66

#: how long a rebalance waits for every member to rejoin before expelling
#: stragglers (the broker-side group.initial.rebalance.delay analog)
_REBALANCE_TIMEOUT_S = 3.0


class _Group:
    """Coordinator-side consumer-group state (GroupMetadata analog).

    States: Empty -> Joining (a rebalance is collecting JoinGroups) ->
    AwaitingSync (generation bumped, leader computing assignment) ->
    Stable.  Any join, leave, or session expiry re-enters Joining;
    members in older generations discover it via errors 22/25/27 and
    rejoin — the real protocol's client contract."""

    __slots__ = ("generation", "members", "leader", "state", "assignments",
                 "offsets", "joined", "deadline")

    def __init__(self):
        self.generation = 0
        #: member_id -> {"sub": bytes, "timeout_ms": int, "last_seen": float}
        self.members: Dict[str, Dict[str, Any]] = {}
        self.leader: Optional[str] = None
        self.state = "Empty"
        self.assignments: Dict[str, bytes] = {}
        #: (topic, partition) -> committed offset
        self.offsets: Dict[Tuple[str, int], int] = {}
        self.joined: set = set()          # members that (re)joined this round
        self.deadline = 0.0


# ---------------------------------------------------------------------------
# broker
# ---------------------------------------------------------------------------

class KafkaWireBroker:
    """A broker speaking the Kafka v0 wire dialect over per-partition logs.

    Real Kafka client libraries can bootstrap against it (ApiVersions →
    Metadata → Produce/Fetch); the in-repo client exercises the same
    frames.  ``directory``: when set, partitions persist as framed
    message-set files and survive restarts."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 directory: Optional[str] = None, node_id: int = 0,
                 users: Optional[Dict[str, str]] = None,
                 ssl_context=None):
        #: SASL credentials (user -> password).  None = open broker;
        #: set = every connection must complete SaslHandshake (PLAIN or
        #: SCRAM-SHA-256) + SaslAuthenticate before any data/metadata API
        #: (unauthenticated requests close the connection, as real
        #: brokers do)
        self.users = users
        #: SCRAM credential hardening (ADVICE r5 #4): real brokers store a
        #: STABLE salted credential per user, not the raw password — here
        #: the stable per-user salt plus a (user, salt, iterations) ->
        #: salted-password cache mean repeated handshakes (including
        #: unauthenticated brute-force attempts) cost ONE 4096-iteration
        #: PBKDF2 ever, not one per attempt.
        self._scram_salts: Dict[str, bytes] = {}
        self._scram_cache: Dict[Tuple[str, bytes, int], bytes] = {}
        #: per-broker secret for DETERMINISTIC decoy salts: an unknown
        #: user's handshake gets the same fake salt on every attempt (a
        #: changing salt would itself leak nonexistence) and fails at the
        #: client-final proof like any wrong password — no username
        #: enumeration, and no PBKDF2 spent on nonexistent users.
        self._scram_decoy_secret = os.urandom(16)
        #: a TLS LISTENER (the reference's ``security.protocol=SSL`` /
        #: SASL_SSL): every accepted connection handshakes TLS before the
        #: first Kafka frame; combine with ``users`` for SASL_SSL
        self._ssl = ssl_context
        self.directory = directory
        self.node_id = node_id
        if directory:
            os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        # derive SCRAM credentials EAGERLY at construction: a lazy first
        # derivation would make a real user's first handshake ~ms slower
        # than a decoy's HMAC — a timing side channel that re-opens the
        # username enumeration the decoy path closes
        for _user in (users or {}):
            self._scram_credentials(_user)
        #: topic -> partition -> list[(offset, key, value, timestamp_ms)]
        self._logs: Dict[str, List[List[Tuple[int, bytes, bytes, int]]]] = {}
        #: KIP-98 transactions: transactional_id -> {pid, epoch, state,
        #: staged {(topic, part): [(key, value, ts), ...]}}.  Transactional
        #: produces buffer broker-side and append ATOMICALLY at EndTxn
        #: commit — the log only ever holds committed data, so every
        #: consumer observes read-committed isolation (the reference broker
        #: appends eagerly and filters via abort markers + LSO instead)
        self._txns: Dict[str, Dict[str, Any]] = {}
        self._next_pid = 1000
        #: committed transactional ids — EndTxn(commit) replays
        #: idempotently (the 2PC sink's recover-and-commit path).  Ordered
        #: dict as a bounded retention window (sinks mint one tid per
        #: checkpoint epoch forever; replays only ever target RECENT
        #: checkpoints, so old entries can age out)
        self._committed_tids: Dict[str, None] = {}
        self._committed_retention = 4096
        #: KIP-227 incremental fetch sessions: session id -> {"epoch",
        #: "parts": {(topic, partition): fetch offset}}.  A FULL fetch
        #: (epoch 0) establishes the session; incremental fetches send
        #: only CHANGED partitions and the response carries only
        #: partitions with news — the steady-state idle poll shrinks to a
        #: near-empty request/response pair
        self._fetch_sessions: Dict[int, dict] = {}
        self._next_session = 1
        #: consumer groups under a dedicated lock: JoinGroup BLOCKS (the
        #: rebalance barrier) and must not hold the log lock while waiting
        self._groups: Dict[str, _Group] = {}
        self._gcond = threading.Condition()
        self._member_seq = 0
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="kafka-broker", daemon=True)
        if directory:
            self._load()

    # -- persistence -------------------------------------------------------
    def _part_path(self, topic: str, part: int) -> str:
        import urllib.parse
        return os.path.join(self.directory,
                            f"{urllib.parse.quote(topic, safe='')}-{part}.log")

    def _load(self) -> None:
        import json
        import urllib.parse
        manifest = os.path.join(self.directory, "_topics.json")
        if os.path.exists(manifest):
            with open(manifest) as f:
                for topic, n in json.load(f).items():
                    self._logs.setdefault(topic, [])
                    while len(self._logs[topic]) < n:
                        self._logs[topic].append([])
        for name in sorted(os.listdir(self.directory)):
            if not name.endswith(".log"):
                continue
            stem = name[:-4]
            tq, _, p = stem.rpartition("-")
            if not tq or not p.isdigit():
                continue                 # not a partition log of ours
            with open(os.path.join(self.directory, name), "rb") as f:
                data = f.read()
            entries = _decode_mixed_log(data)
            topic = urllib.parse.unquote(tq)
            parts = self._logs.setdefault(topic, [])
            while len(parts) <= int(p):
                parts.append([])
            parts[int(p)] = list(entries)
        goff = os.path.join(self.directory, "_groups.json")
        if os.path.exists(goff):
            with open(goff) as f:
                for gid, offs in json.load(f).items():
                    g = self._groups.setdefault(gid, _Group())
                    for key, off in offs.items():
                        topic, _, part = key.rpartition("@")
                        g.offsets[(topic, int(part))] = off
        tcf = os.path.join(self.directory, "_txn_commits.json")
        if os.path.exists(tcf):
            with open(tcf) as f:
                self._committed_tids = dict.fromkeys(json.load(f))
        self._load_txns()

    def _persist_txn_commits_locked(self) -> None:
        """Committed transactional ids survive restarts: a 2PC sink's
        recover-and-commit replay must stay idempotent across broker
        crashes (the __transaction_state topic analog)."""
        if not self.directory:
            return
        import json
        tmp = os.path.join(self.directory, "_txn_commits.json#tmp")
        with open(tmp, "w") as f:
            json.dump(list(self._committed_tids), f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.directory, "_txn_commits.json"))

    def _persist_group_offsets_locked(self) -> None:
        """Committed offsets survive broker restarts (the __consumer_offsets
        topic analog).  Caller holds ``_gcond``."""
        if not self.directory:
            return
        import json
        payload = {gid: {f"{t}@{p}": off
                         for (t, p), off in g.offsets.items()}
                   for gid, g in self._groups.items() if g.offsets}
        tmp = os.path.join(self.directory, "_groups.json#tmp")
        with open(tmp, "w") as f:
            json.dump(payload, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, os.path.join(self.directory, "_groups.json"))

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            parts = self._logs.setdefault(topic, [])
            while len(parts) < partitions:
                parts.append([])
            self._persist_manifest_locked()

    def _persist_manifest_locked(self) -> None:
        """Topic/partition METADATA must survive restarts too — an empty
        partition that vanished would fail keyed producers with
        UNKNOWN_TOPIC after a restart."""
        if not self.directory:
            return
        import json
        tmp = os.path.join(self.directory, "_topics.json#tmp")
        with open(tmp, "w") as f:
            json.dump({t: len(p) for t, p in self._logs.items()}, f)
            f.flush()
            os.fsync(f.fileno())   # as durable as the logs it describes
        os.replace(tmp, os.path.join(self.directory, "_topics.json"))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "KafkaWireBroker":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve_conn, args=(conn,),
                             daemon=True).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        """Per-connection entry: the TLS handshake (when configured) runs
        HERE, on the connection's own thread with a timeout — a silent
        peer must never wedge the single accept loop."""
        if self._ssl is not None:
            try:
                conn.settimeout(30)
                conn = self._ssl.wrap_socket(conn, server_side=True)
            except (OSError, ValueError):
                # plaintext/bad-cert peers never reach the frame loop
                try:
                    conn.close()
                except OSError:
                    pass
                return
        self._serve(conn)

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(60)
        # per-connection SASL session state (a real broker authenticates
        # the CONNECTION, not individual requests)
        state = {"authenticated": self.users is None, "mechanism": None}
        try:
            while not self._stop.is_set():
                hdr = self._recv_exact(conn, 4)
                if hdr is None:
                    return
                (size,) = struct.unpack(">i", hdr)
                frame = self._recv_exact(conn, size)
                if frame is None:
                    return
                resp = self._handle(frame, state)
                if resp is None:
                    return                      # unsupported request: close
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        except (OSError, EOFError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _recv_exact(conn, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- request dispatch --------------------------------------------------
    def _handle(self, frame: bytes,
                state: Optional[Dict[str, Any]] = None) -> Optional[bytes]:
        if state is None:
            # direct callers get the same auth posture as a fresh
            # connection — defaulting to authenticated would silently
            # bypass SASL on a credentialed broker
            state = {"authenticated": self.users is None, "mechanism": None}
        r = _Reader(frame)
        api_key = r.int16()
        api_version = r.int16()
        correlation = r.int32()
        client_id = r.string()
        w = _Writer().int32(correlation)
        if not state["authenticated"] and api_key not in (
                _API_VERSIONS, _API_SASL_HANDSHAKE, _API_SASL_AUTHENTICATE):
            return None  # real brokers drop unauthenticated connections
        if api_key == _API_VERSIONS:
            w.int16(_ERR_NONE).array(
                [(_API_PRODUCE, 0, 3), (_API_FETCH, 0, 7),
                 (_API_LIST_OFFSETS, 0, 0), (_API_METADATA, 0, 0),
                 (_API_OFFSET_COMMIT, 2, 2), (_API_OFFSET_FETCH, 1, 1),
                 (_API_FIND_COORDINATOR, 0, 0), (_API_JOIN_GROUP, 0, 0),
                 (_API_HEARTBEAT, 0, 0), (_API_LEAVE_GROUP, 0, 0),
                 (_API_SYNC_GROUP, 0, 0), (_API_VERSIONS, 0, 0),
                 # v1+ only: the v0 handshake's RAW post-handshake token
                 # frames (no request header) are not spoken here
                 (_API_SASL_HANDSHAKE, 1, 1),
                 (_API_SASL_AUTHENTICATE, 0, 0),
                 (_API_INIT_PRODUCER_ID, 0, 0),
                 (_API_ADD_PARTITIONS_TO_TXN, 0, 0),
                 (_API_END_TXN, 0, 0),
                 (_API_LIST_TRANSACTIONS, 0, 0)],
                lambda w, t: w.int16(t[0]).int16(t[1]).int16(t[2]))
        elif api_key == _API_SASL_HANDSHAKE:
            mech = (r.string() or "").upper()
            if mech not in ("PLAIN", "SCRAM-SHA-256"):
                w.int16(_ERR_UNSUPPORTED_SASL_MECHANISM)
            else:
                state["mechanism"] = mech
                w.int16(_ERR_NONE)
            w.array(["PLAIN", "SCRAM-SHA-256"], lambda w, m: w.string(m))
        elif api_key == _API_SASL_AUTHENTICATE:
            token = r.bytes_() or b""
            mech = state.get("mechanism")
            if mech == "PLAIN":
                # PLAIN token: [authzid] NUL user NUL password (RFC 4616)
                parts = token.split(b"\0")
                user = parts[1].decode() if len(parts) == 3 else ""
                pw = parts[2].decode() if len(parts) == 3 else ""
                want = (self.users or {}).get(user)
                if want is not None and pw == want:
                    state["authenticated"] = True
                    w.int16(_ERR_NONE).string(None).bytes_(b"")
                else:
                    w.int16(_ERR_SASL_AUTHENTICATION_FAILED) \
                        .string(f"authentication failed for user "
                                f"{user!r}").bytes_(b"")
            elif mech == "SCRAM-SHA-256":
                self._sasl_scram(state, token, w)
            else:
                w.int16(_ERR_ILLEGAL_SASL_STATE) \
                    .string("SaslHandshake must precede authentication") \
                    .bytes_(b"")
        elif api_key == _API_METADATA:
            self._metadata(r, w)
        elif api_key == _API_PRODUCE and api_version == 0:
            self._produce(r, w)
        elif api_key == _API_PRODUCE and api_version == 3:
            self._produce_v3(r, w)
        elif api_key == _API_FETCH and api_version == 0:
            self._fetch(r, w)
        elif api_key == _API_FETCH and api_version == 4:
            self._fetch_v4(r, w)
        elif api_key == _API_FETCH and api_version == 7:
            self._fetch_v7(r, w)
        elif api_key == _API_LIST_OFFSETS and api_version == 0:
            self._list_offsets(r, w)
        elif api_key == _API_FIND_COORDINATOR:
            self._find_coordinator(r, w)
        elif api_key == _API_JOIN_GROUP:
            self._join_group(r, w, client_id)
        elif api_key == _API_SYNC_GROUP:
            self._sync_group(r, w)
        elif api_key == _API_HEARTBEAT:
            self._heartbeat(r, w)
        elif api_key == _API_LEAVE_GROUP:
            self._leave_group(r, w)
        elif api_key == _API_INIT_PRODUCER_ID:
            self._init_producer_id(r, w)
        elif api_key == _API_ADD_PARTITIONS_TO_TXN:
            self._add_partitions_to_txn(r, w)
        elif api_key == _API_END_TXN:
            self._end_txn(r, w)
        elif api_key == _API_LIST_TRANSACTIONS:
            self._list_transactions(r, w)
        elif api_key == _API_OFFSET_COMMIT and api_version == 2:
            self._offset_commit(r, w)
        elif api_key == _API_OFFSET_FETCH and api_version == 1:
            self._offset_fetch(r, w)
        else:
            # unsupported api/version: close the connection, the v0-era
            # broker behavior — a clean client-side error, never a hang
            return None
        return w.done()

    # -- consumer groups (GroupCoordinator / GroupMetadataManager analog) --
    def _expire_members_locked(self, g: _Group) -> None:
        now = time.time()
        dead = [m for m, info in g.members.items()
                if now - info["last_seen"] > info["timeout_ms"] / 1000.0]
        for m in dead:
            del g.members[m]
            g.joined.discard(m)
        if dead and g.members and g.state == "Stable":
            g.state = "Joining"
            g.joined = set()
            g.deadline = now + _REBALANCE_TIMEOUT_S
            self._gcond.notify_all()
        if not g.members:
            g.state = "Empty"

    def _find_coordinator(self, r: _Reader, w: _Writer) -> None:
        r.string()                              # group id: we coordinate all
        w.int16(_ERR_NONE).int32(self.node_id).string(self.host) \
            .int32(self.port)

    def _join_group(self, r: _Reader, w: _Writer,
                    client_id: Optional[str]) -> None:
        group_id = r.string()
        session_timeout = r.int32()
        member_id = r.string() or ""
        r.string()                              # protocol_type
        protos = r.array(lambda r: (r.string(), r.bytes_()))
        sub = protos[0][1] if protos else b""
        with self._gcond:
            g = self._groups.setdefault(group_id, _Group())
            self._expire_members_locked(g)
            if member_id and member_id not in g.members:
                # deposed member retrying with a stale id: reset it
                w.int16(_ERR_UNKNOWN_MEMBER_ID).int32(-1).string("") \
                    .string("").string(member_id) \
                    .array([], lambda w, x: None)
                return
            if not member_id:
                self._member_seq += 1
                member_id = f"{client_id or 'member'}-{self._member_seq}"
            if g.state != "Joining":
                # any join (re)starts a rebalance round; members of the old
                # generation discover via Heartbeat/SyncGroup error 27
                g.state = "Joining"
                g.joined = set()
                g.deadline = time.time() + _REBALANCE_TIMEOUT_S
            g.members[member_id] = {"sub": sub, "timeout_ms": session_timeout,
                                    "last_seen": time.time()}
            g.joined.add(member_id)
            self._gcond.notify_all()
            # the rebalance BARRIER: wait until every known member rejoined
            # this round, expelling stragglers at the deadline
            while g.state == "Joining":
                # re-assert OUR membership every iteration: a concurrent
                # leave/expiry restarts the round with a cleared joined set,
                # and a member blocked right here must never be expelled as
                # a straggler of the round it is actively waiting in
                g.members.setdefault(
                    member_id, {"sub": sub, "timeout_ms": session_timeout,
                                "last_seen": time.time()})
                g.joined.add(member_id)
                missing = set(g.members) - g.joined
                now = time.time()
                if not missing or now >= g.deadline:
                    for m in missing:
                        del g.members[m]
                    g.generation += 1
                    g.leader = min(g.members) if g.members else None
                    g.assignments = {}
                    g.state = "AwaitingSync"
                    self._gcond.notify_all()
                    break
                self._gcond.wait(
                    timeout=max(0.01, min(0.25, g.deadline - now)))
            members = ([(m, info["sub"])
                        for m, info in sorted(g.members.items())]
                       if g.leader == member_id else [])
            w.int16(_ERR_NONE).int32(g.generation).string("range") \
                .string(g.leader or "").string(member_id)
            w.array(members, lambda w, p: w.string(p[0]).bytes_(p[1]))

    def _sync_group(self, r: _Reader, w: _Writer) -> None:
        r_group = r.string()
        generation = r.int32()
        member_id = r.string()
        assignment_list = r.array(lambda r: (r.string(), r.bytes_()))
        with self._gcond:
            g = self._groups.get(r_group)
            if g is None or member_id not in g.members:
                w.int16(_ERR_UNKNOWN_MEMBER_ID).bytes_(None)
                return
            if g.state == "Joining":
                w.int16(_ERR_REBALANCE_IN_PROGRESS).bytes_(None)
                return
            if generation != g.generation:
                w.int16(_ERR_ILLEGAL_GENERATION).bytes_(None)
                return
            if member_id == g.leader and assignment_list:
                g.assignments = dict(assignment_list)
                g.state = "Stable"
                self._gcond.notify_all()
            deadline = time.time() + _REBALANCE_TIMEOUT_S
            while g.state == "AwaitingSync" and generation == g.generation:
                now = time.time()
                if now >= deadline:
                    break
                self._gcond.wait(
                    timeout=max(0.01, min(0.25, deadline - now)))
            if generation != g.generation or g.state != "Stable":
                w.int16(_ERR_REBALANCE_IN_PROGRESS).bytes_(None)
                return
            g.members[member_id]["last_seen"] = time.time()
            w.int16(_ERR_NONE).bytes_(g.assignments.get(member_id, b""))

    def _heartbeat(self, r: _Reader, w: _Writer) -> None:
        group_id = r.string()
        generation = r.int32()
        member_id = r.string()
        with self._gcond:
            g = self._groups.get(group_id)
            if g is None or member_id not in g.members:
                w.int16(_ERR_UNKNOWN_MEMBER_ID)
                return
            if g.state == "Joining":
                w.int16(_ERR_REBALANCE_IN_PROGRESS)
                return
            if generation != g.generation:
                w.int16(_ERR_ILLEGAL_GENERATION)
                return
            g.members[member_id]["last_seen"] = time.time()
            w.int16(_ERR_NONE)

    def _leave_group(self, r: _Reader, w: _Writer) -> None:
        group_id = r.string()
        member_id = r.string()
        with self._gcond:
            g = self._groups.get(group_id)
            if g is None or member_id not in g.members:
                w.int16(_ERR_UNKNOWN_MEMBER_ID)
                return
            del g.members[member_id]
            g.joined.discard(member_id)
            if g.members:
                g.state = "Joining"
                g.joined = set()
                g.deadline = time.time() + _REBALANCE_TIMEOUT_S
                self._gcond.notify_all()
            else:
                g.state = "Empty"
            w.int16(_ERR_NONE)

    def _offset_commit(self, r: _Reader, w: _Writer) -> None:
        group_id = r.string()
        generation = r.int32()
        member_id = r.string()
        r.int64()                               # retention_time
        results = []
        with self._gcond:
            g = self._groups.setdefault(group_id, _Group())
            # generation fencing: a deposed member's commit is rejected
            # (generation -1 + empty member = the simple-client escape)
            fenced = (generation >= 0
                      and (generation != g.generation
                           or member_id not in g.members))
            for _ in range(r.int32()):
                topic = r.string()
                per = []
                for _ in range(r.int32()):
                    part = r.int32()
                    off = r.int64()
                    r.string()                  # metadata
                    if fenced:
                        per.append((part, _ERR_ILLEGAL_GENERATION))
                    else:
                        g.offsets[(topic, part)] = off
                        per.append((part, _ERR_NONE))
                results.append((topic, per))
            if not fenced:
                self._persist_group_offsets_locked()
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1])))

    def _offset_fetch(self, r: _Reader, w: _Writer) -> None:
        group_id = r.string()
        with self._gcond:
            g = self._groups.get(group_id)
            results = []
            for _ in range(r.int32()):
                topic = r.string()
                per = []
                for _ in range(r.int32()):
                    part = r.int32()
                    off = g.offsets.get((topic, part), -1) if g else -1
                    per.append((part, off))
                results.append((topic, per))
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int64(p[1]).string("")
            .int16(_ERR_NONE)))

    def _scram_credentials(self, user: str) -> Tuple[bytes, bytes]:
        """(salt, salted_password) for a user — stable salt + cached
        PBKDF2 for known users; a deterministic DECOY pair for unknown
        users (HMAC of the per-broker secret — zero PBKDF2 cost, same
        salt on every probe, proof can never verify)."""
        import hashlib as _hl
        import hmac as _hmac

        want = (self.users or {}).get(user)
        with self._lock:
            if want is None:
                salt = _hmac.new(self._scram_decoy_secret,
                                 b"salt:" + user.encode(),
                                 _hl.sha256).digest()[:16]
                salted = _hmac.new(self._scram_decoy_secret, b"decoy-key",
                                   _hl.sha256).digest()
                return salt, salted
            salt = self._scram_salts.get(user)
            if salt is None:
                salt = self._scram_salts[user] = os.urandom(16)
            key = (user, salt, 4096)
            salted = self._scram_cache.get(key)
            if salted is None:
                salted = self._scram_cache[key] = _hl.pbkdf2_hmac(
                    "sha256", want.encode(), salt, 4096)
            return salt, salted

    def _sasl_scram(self, state: dict, token: bytes, w: _Writer) -> None:
        """SCRAM-SHA-256 over SaslAuthenticate (two rounds: client-first →
        server-first, client-final → server-final).  The RFC 5802 math is
        the shared ``flink_tpu.security.scram`` implementation — same
        code the Postgres handshake uses.

        Unknown users are indistinguishable from wrong passwords: round 1
        answers with a deterministic decoy salt and the exchange fails at
        the round-2 proof — the pre-hardening behaviour (an immediate
        "authentication failed for user X") let an attacker enumerate
        valid usernames without knowing any password."""
        from flink_tpu.security.scram import ScramServer

        try:
            text = token.decode()
            srv = state.get("scram")
            if srv is None:                   # round 1: client-first
                srv = ScramServer(iterations=4096)
                user = ScramServer.username_of(text)
                salt, salted = self._scram_credentials(user)
                state["scram"] = srv
                first = srv.first_response(text, salt=salt, salted=salted)
                w.int16(_ERR_NONE).string(None).bytes_(first.encode())
                return
            ok, final = srv.verify_final(text)  # round 2: client-final
            state.pop("scram", None)
            if ok:
                state["authenticated"] = True
                w.int16(_ERR_NONE).string(None).bytes_(final.encode())
            else:
                w.int16(_ERR_SASL_AUTHENTICATION_FAILED) \
                    .string("SCRAM proof verification failed").bytes_(b"")
        except (ValueError, KeyError, IndexError, UnicodeDecodeError) as e:
            state.pop("scram", None)
            w.int16(_ERR_SASL_AUTHENTICATION_FAILED) \
                .string(f"malformed SCRAM message: "
                        f"{e or type(e).__name__}").bytes_(b"")

    def _metadata(self, r: _Reader, w: _Writer) -> None:
        want = r.array(lambda r: r.string())
        with self._lock:
            topics = sorted(self._logs) if not want else list(want)
            w.array([(self.node_id, self.host, self.port)],
                    lambda w, b: w.int32(b[0]).string(b[1]).int32(b[2]))

            def topic_meta(w, t):
                parts = self._logs.get(t)
                if parts is None:
                    w.int16(_ERR_UNKNOWN_TOPIC).string(t).int32(0)
                    return
                w.int16(_ERR_NONE).string(t)
                w.array(list(range(len(parts))),
                        lambda w, p: w.int16(_ERR_NONE).int32(p)
                        .int32(self.node_id)
                        .array([self.node_id], lambda w, x: w.int32(x))
                        .array([self.node_id], lambda w, x: w.int32(x)))

            w.array(topics, topic_meta)

    def _produce(self, r: _Reader, w: _Writer) -> None:
        r.int16()                               # required_acks
        r.int32()                               # timeout
        results = []
        for _ in range(r.int32()):
            topic = r.string()
            per_part = []
            for _ in range(r.int32()):
                part = r.int32()
                mset = r.bytes_() or b""
                try:
                    entries = decode_message_set(mset)
                except ValueError:
                    per_part.append((part, _ERR_UNKNOWN, -1))
                    continue
                base = self._append(topic, part,
                                    [(k, v, -1) for _o, k, v in entries])
                per_part.append((part, _ERR_NONE, base) if base >= 0
                                else (part, _ERR_UNKNOWN_TOPIC, -1))
            results.append((topic, per_part))
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1]).int64(p[2])))

    def _append(self, topic: str, part: int,
                records: List[Tuple[Optional[bytes], Optional[bytes], int]]
                ) -> int:
        with self._lock:
            return self._append_locked(topic, part, records)

    def _append_locked(self, topic: str, part: int,
                       records: List[Tuple[Optional[bytes],
                                           Optional[bytes], int]]) -> int:
        """Append (key, value, ts) records; returns base offset or -1 for an
        unknown topic/partition.  Disk persistence uses the v2 record-batch
        format (richer: keeps timestamps); v0 produces store ts=-1.
        Caller holds ``_lock`` (EndTxn commits several partitions under ONE
        acquisition — the atomicity of the commit)."""
        parts = self._logs.get(topic)
        if parts is None or not 0 <= part < len(parts):
            return -1
        base = len(parts[part])
        stored = [(base + i, k, v, ts)
                  for i, (k, v, ts) in enumerate(records)]
        parts[part].extend(stored)
        if self.directory:
            batch = _encode_batch_v2(
                base, [(max(ts, 0), k, v, []) for _o, k, v, ts in stored])
            with open(self._part_path(topic, part), "ab") as f:
                f.write(batch)
                f.flush()
                os.fsync(f.fileno())
        return base

    # -- transactions (KIP-98: InitProducerId / AddPartitionsToTxn /
    # EndTxn; ListTransactions for recovery enumeration) -------------------
    def _txn_path(self, tid: str) -> str:
        import urllib.parse
        return os.path.join(self.directory,
                            f"_txn-{urllib.parse.quote(tid, safe='')}.pkl")

    def _persist_txn_locked(self, tid: str) -> None:
        """OPEN (pre-committed) transactions survive broker restarts: the
        2PC sink's crash window between pre-commit and commit must not
        lose the staged records to a broker crash.  The file is a pickle
        STREAM — a small meta record followed by one appended segment per
        transactional produce (O(n) total I/O; a full rewrite per produce
        would be quadratic in epoch size).  This writes/truncates the META
        record; ``_append_txn_segment_locked`` appends data.  Caller holds
        ``_lock``."""
        if not self.directory:
            return
        import pickle
        txn = self._txns.get(tid)
        if txn is None:
            return
        # atomic replace: a crash mid-rewrite must not destroy already
        # fsynced (acked) staged records — the old file stays whole until
        # the new one is durable
        tmp = self._txn_path(tid) + "#tmp"
        with open(tmp, "wb") as f:
            pickle.dump({"meta": True, "pid": txn["pid"],
                         "epoch": txn["epoch"], "state": txn["state"]},
                        f, protocol=pickle.HIGHEST_PROTOCOL)
            for (t, p), recs in txn["staged"].items():
                if recs:
                    pickle.dump((t, p, recs), f,
                                protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, self._txn_path(tid))

    def _append_txn_segment_locked(self, tid: str, topic: str, part: int,
                                   recs: list) -> None:
        """Append one produce's records to the txn file (durable staging
        without rewriting the whole buffer).  Caller holds ``_lock``."""
        if not self.directory or not recs:
            return
        import pickle
        path = self._txn_path(tid)
        if not os.path.exists(path):
            self._persist_txn_locked(tid)
            return               # meta write above already included recs
        with open(path, "ab") as f:
            pickle.dump((topic, part, recs), f,
                        protocol=pickle.HIGHEST_PROTOCOL)
            f.flush()
            os.fsync(f.fileno())

    def _remove_txn_file_locked(self, tid: str) -> None:
        if not self.directory:
            return
        try:
            os.remove(self._txn_path(tid))
        except FileNotFoundError:
            pass

    def _load_txns(self) -> None:
        import pickle
        import urllib.parse
        for name in os.listdir(self.directory):
            if not (name.startswith("_txn-") and name.endswith(".pkl")):
                continue
            tid = urllib.parse.unquote(name[len("_txn-"):-len(".pkl")])
            staged: Dict[Any, list] = {}
            meta = None
            try:
                with open(os.path.join(self.directory, name), "rb") as f:
                    meta = pickle.load(f)
                    while True:
                        t, p, recs = pickle.load(f)
                        staged.setdefault((t, int(p)), []).extend(recs)
            except EOFError:
                pass             # normal end of the segment stream
            except (OSError, pickle.PickleError):
                pass             # torn tail: keep the complete prefix
            if not isinstance(meta, dict) or not meta.get("meta"):
                continue         # unreadable meta: the txn aborts
            self._txns[tid] = {"pid": meta["pid"], "epoch": meta["epoch"],
                               "state": meta["state"], "staged": staged}
            self._next_pid = max(self._next_pid, meta["pid"] + 1)

    def _init_producer_id(self, r: _Reader, w: _Writer) -> None:
        tid = r.string()
        r.int32()                               # transaction_timeout_ms
        with self._lock:
            if tid is None:
                pid, epoch = self._next_pid, 0
                self._next_pid += 1
            else:
                cur = self._txns.get(tid)
                if cur is None:
                    pid, epoch = self._next_pid, 0
                    self._next_pid += 1
                    self._txns[tid] = {"pid": pid, "epoch": 0,
                                       "state": "ready", "staged": {}}
                else:
                    # zombie fencing: same tid re-initializes with a BUMPED
                    # epoch and the old incarnation's ongoing txn aborts
                    pid = cur["pid"]
                    epoch = cur["epoch"] + 1
                    cur.update(epoch=epoch, state="ready", staged={})
                self._persist_txn_locked(tid)
        w.int32(0).int16(_ERR_NONE).int64(pid).int16(epoch)

    def _txn_check_locked(self, tid, pid, epoch):
        txn = self._txns.get(tid)
        if txn is None:
            return None, _ERR_INVALID_TXN_STATE
        if txn["pid"] != pid or txn["epoch"] != epoch:
            return None, _ERR_INVALID_PRODUCER_EPOCH
        return txn, _ERR_NONE

    def _add_partitions_to_txn(self, r: _Reader, w: _Writer) -> None:
        tid = r.string()
        pid = r.int64()
        epoch = r.int16()
        topics = r.array(lambda r: (r.string(),
                                    r.array(lambda r: r.int32())))
        with self._lock:
            txn, err = self._txn_check_locked(tid, pid, epoch)
            part_errs: Dict[Tuple[str, int], int] = {}
            if err == _ERR_NONE:
                txn["state"] = "ongoing"
                for t, ps in topics:
                    parts = self._logs.get(t)
                    for p in ps:
                        if parts is None or not 0 <= p < len(parts):
                            part_errs[(t, p)] = _ERR_UNKNOWN_TOPIC
                        else:
                            txn["staged"].setdefault((t, p), [])
                self._persist_txn_locked(tid)
        w.int32(0).array(topics, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p).int16(
                part_errs.get((t[0], p), err) if err == _ERR_NONE
                else err)))

    def _end_txn(self, r: _Reader, w: _Writer) -> None:
        tid = r.string()
        pid = r.int64()
        epoch = r.int16()
        commit = r.int8() != 0
        with self._lock:
            if tid not in self._txns:
                # no OPEN txn under this id: a commit replay of an already
                # committed one is idempotent (the recover-and-commit
                # path); anything else is an error.  The check must not
                # swallow a NEW txn reusing a previously committed id —
                # only absent ids answer from the committed set.
                if commit and tid in self._committed_tids:
                    w.int32(0).int16(_ERR_NONE)
                else:
                    w.int32(0).int16(_ERR_INVALID_TXN_STATE)
                return
            txn, err = self._txn_check_locked(tid, pid, epoch)
            if err != _ERR_NONE:
                w.int32(0).int16(err)
                return
            if commit:
                # ONE lock acquisition spans every partition append: the
                # whole transaction becomes visible atomically (partitions
                # were validated at staging time; -1 here is impossible)
                for (t, p), recs in sorted(txn["staged"].items()):
                    if recs:
                        base = self._append_locked(t, p, recs)
                        assert base >= 0, (t, p)
                self._committed_tids[tid] = None
                while len(self._committed_tids) > self._committed_retention:
                    self._committed_tids.pop(
                        next(iter(self._committed_tids)))
                self._persist_txn_commits_locked()
            del self._txns[tid]
            self._remove_txn_file_locked(tid)
        w.int32(0).int16(_ERR_NONE)

    def _list_transactions(self, r: _Reader, w: _Writer) -> None:
        with self._lock:
            entries = [(t, x["pid"], x["epoch"], x["state"])
                       for t, x in self._txns.items()]
        w.int32(0).int16(_ERR_NONE).array(
            entries, lambda w, e: w.string(e[0]).int64(e[1]).int16(e[2])
            .string(e[3]))

    def _fetch(self, r: _Reader, w: _Writer) -> None:
        r.int32()                               # replica_id
        r.int32()                               # max_wait
        r.int32()                               # min_bytes
        results = []
        for _ in range(r.int32()):
            topic = r.string()
            per_part = []
            for _ in range(r.int32()):
                part = r.int32()
                offset = r.int64()
                max_bytes = r.int32()
                with self._lock:
                    parts = self._logs.get(topic)
                    if parts is None or not 0 <= part < len(parts):
                        per_part.append((part, _ERR_UNKNOWN_TOPIC, -1, b""))
                        continue
                    log = parts[part]
                    hw = len(log)
                    if offset > hw or offset < 0:
                        per_part.append((part, _ERR_OFFSET_OUT_OF_RANGE,
                                         hw, b""))
                        continue
                    take, size = [], 0
                    for o, k, v, _ts in log[offset:]:
                        m = encode_message_set([(o, k, v)])   # encode ONCE
                        if take and size + len(m) > max_bytes:
                            break
                        take.append(m)
                        size += len(m)
                per_part.append((part, _ERR_NONE, hw, b"".join(take)))
            results.append((topic, per_part))
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1]).int64(p[2])
            .bytes_(p[3])))

    def _produce_v3(self, r: _Reader, w: _Writer) -> None:
        tid = r.string()                        # transactional_id
        r.int16()                               # required_acks
        r.int32()                               # timeout
        results = []
        for _ in range(r.int32()):
            topic = r.string()
            per_part = []
            for _ in range(r.int32()):
                part = r.int32()
                data = r.bytes_() or b""
                try:
                    recs = _decode_batches_v2(data)
                except ValueError:
                    per_part.append((part, _ERR_UNKNOWN, -1))
                    continue
                if tid is not None:
                    # transactional: records stage in the txn buffer (the
                    # batch's producer id/epoch fence zombie writers) and
                    # reach the log only at EndTxn commit
                    from flink_tpu.connectors.kafka_v2 import \
                        batch_producer_info
                    pid, pepoch, _txl = batch_producer_info(data)
                    with self._lock:
                        txn, err = self._txn_check_locked(tid, pid, pepoch)
                        if err == _ERR_NONE and txn["state"] != "ongoing":
                            err = _ERR_INVALID_TXN_STATE
                        elif err == _ERR_NONE:
                            parts = self._logs.get(topic)
                            if parts is None or not 0 <= part < len(parts):
                                # validate at STAGING time: the commit
                                # appends unconditionally, so an unknown
                                # partition acked here would silently
                                # vanish at EndTxn
                                err = _ERR_UNKNOWN_TOPIC
                            else:
                                staged = [(k, v, ts)
                                          for _o, ts, k, v, _h in recs]
                                txn["staged"].setdefault(
                                    (topic, part), []).extend(staged)
                                self._append_txn_segment_locked(
                                    tid, topic, part, staged)
                    per_part.append((part, err, -1))
                    continue
                base = self._append(topic, part,
                                    [(k, v, ts) for _o, ts, k, v, _h in recs])
                per_part.append((part, _ERR_NONE, base) if base >= 0
                                else (part, _ERR_UNKNOWN_TOPIC, -1))
            results.append((topic, per_part))
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1]).int64(p[2])
            .int64(-1)))                        # log_append_time
        w.int32(0)                              # throttle_time_ms

    def _fetch_v4(self, r: _Reader, w: _Writer) -> None:
        r.int32()                               # replica_id
        r.int32()                               # max_wait
        r.int32()                               # min_bytes
        r.int32()                               # max_bytes (response-wide)
        r.int8()                                # isolation_level
        results = []
        for _ in range(r.int32()):
            topic = r.string()
            per_part = []
            for _ in range(r.int32()):
                part = r.int32()
                offset = r.int64()
                max_bytes = r.int32()
                err, hw, data = self._read_partition_window(
                    topic, part, offset, max_bytes)
                per_part.append((part, err, hw, data))
            results.append((topic, per_part))
        w.int32(0)                              # throttle_time_ms
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1]).int64(p[2])
            .int64(p[2])                        # last_stable_offset = hw
            .array([], lambda w, x: None)       # aborted transactions
            .bytes_(p[3])))

    def _read_partition_window(self, topic: str, part: int, offset: int,
                               max_bytes: int):
        """(error, high_watermark, record batch bytes) for one fetch
        window — shared by the v4 and v7 fetch handlers.  Caller holds no
        lock."""
        with self._lock:
            parts = self._logs.get(topic)
            if parts is None or not 0 <= part < len(parts):
                return _ERR_UNKNOWN_TOPIC, -1, b""
            log = parts[part]
            hw = len(log)
            if offset > hw or offset < 0:
                return _ERR_OFFSET_OUT_OF_RANGE, hw, b""
            take = []
            size = 0
            for o, k, v, ts in log[offset:]:
                rec = (len(k or b"") + len(v or b"") + 32)
                if take and size + rec > max_bytes:
                    break
                take.append((max(ts, 0), k, v, []))
                size += rec
            data = (_encode_batch_v2(offset, take) if take else b"")
        return _ERR_NONE, hw, data

    def _fetch_v7(self, r: _Reader, w: _Writer) -> None:
        """Fetch v7 with KIP-227 incremental fetch sessions."""
        r.int32()                               # replica_id
        r.int32()                               # max_wait
        r.int32()                               # min_bytes
        r.int32()                               # max_bytes (response-wide)
        r.int8()                                # isolation_level
        session_id = r.int32()
        epoch = r.int32()
        req_parts: List[Tuple[str, int, int, int]] = []
        for _ in range(r.int32()):
            topic = r.string()
            for _ in range(r.int32()):
                part = r.int32()
                offset = r.int64()
                r.int64()                       # log_start_offset
                max_bytes = r.int32()
                req_parts.append((topic, part, offset, max_bytes))
        forgotten: List[Tuple[str, int]] = []
        for _ in range(r.int32()):
            topic = r.string()
            for _ in range(r.int32()):
                forgotten.append((topic, r.int32()))

        def reply_error(code: int) -> None:
            w.int32(0).int16(code).int32(session_id) \
                .array([], lambda w, x: None)

        with self._lock:
            if epoch in (0, -1):
                if epoch == -1:
                    # KIP-227 session CLOSE: drop the named session and
                    # serve this one request sessionless
                    self._fetch_sessions.pop(session_id, None)
                    session_id = 0
                else:
                    # FULL fetch establishes a new session (bounded
                    # registry: oldest sessions age out, like
                    # _committed_tids)
                    session_id = self._next_session
                    self._next_session += 1
                    self._fetch_sessions[session_id] = {
                        "epoch": 1,
                        "parts": {(t, p): (o, mb)
                                  for t, p, o, mb in req_parts}}
                    while len(self._fetch_sessions) > 1024:
                        self._fetch_sessions.pop(
                            next(iter(self._fetch_sessions)))
                sess_parts = {(t, p): (o, mb) for t, p, o, mb in req_parts}
                full = True
            else:
                sess = self._fetch_sessions.get(session_id)
                if sess is None:
                    return reply_error(_ERR_FETCH_SESSION_ID_NOT_FOUND)
                if epoch != sess["epoch"]:
                    return reply_error(_ERR_INVALID_FETCH_SESSION_EPOCH)
                sess["epoch"] += 1
                # LRU: re-insert on each successful incremental fetch so
                # the bounded-registry eviction below removes the least
                # recently USED session, not the oldest ESTABLISHED — an
                # actively-polling consumer is never spuriously evicted
                self._fetch_sessions[session_id] = \
                    self._fetch_sessions.pop(session_id)
                for t, p in forgotten:
                    sess["parts"].pop((t, p), None)
                for t, p, o, mb in req_parts:   # adds AND offset updates
                    sess["parts"][(t, p)] = (o, mb)
                sess_parts = dict(sess["parts"])
                full = False

        by_topic: Dict[str, List[tuple]] = {}
        for (topic, part), (offset, max_bytes) in sess_parts.items():
            err, hw, data = self._read_partition_window(
                topic, part, offset, max_bytes)
            if not full and err == _ERR_NONE and not data:
                continue    # incremental: only partitions with NEWS
            by_topic.setdefault(topic, []).append((part, err, hw, data))
        w.int32(0)                              # throttle_time_ms
        w.int16(_ERR_NONE)
        w.int32(session_id)
        w.array(sorted(by_topic.items()), lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1]).int64(p[2])
            .int64(p[2])                        # last_stable_offset = hw
            .int64(0)                           # log_start_offset
            .array([], lambda w, x: None)       # aborted transactions
            .bytes_(p[3])))

    def _list_offsets(self, r: _Reader, w: _Writer) -> None:
        r.int32()                               # replica_id
        results = []
        for _ in range(r.int32()):
            topic = r.string()
            per_part = []
            for _ in range(r.int32()):
                part = r.int32()
                time_ms = r.int64()
                r.int32()                       # max_num_offsets
                with self._lock:
                    parts = self._logs.get(topic)
                    if parts is None or not 0 <= part < len(parts):
                        per_part.append((part, _ERR_UNKNOWN_TOPIC, []))
                        continue
                    hw = len(parts[part])
                # -1 = latest, -2 = earliest (the protocol's sentinels)
                per_part.append((part, _ERR_NONE,
                                 [hw] if time_ms == -1 else [0]))
            results.append((topic, per_part))
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1])
            .array(p[2], lambda w, o: w.int64(o))))


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class KafkaWireClient:
    """Produce/fetch against any broker speaking the v0 dialect."""

    def __init__(self, host: str, port: int, client_id: str = "flink-tpu",
                 timeout_s: float = 30.0, username: Optional[str] = None,
                 password: str = "",
                 sasl_mechanism: str = "PLAIN", ssl_context=None):
        self.host, self.port = host, port
        self.client_id = client_id
        self.timeout_s = timeout_s
        #: SASL credentials; when set, every (re)connection runs
        #: SaslHandshake + SaslAuthenticate before the first data API.
        #: Mechanisms: PLAIN or SCRAM-SHA-256 (RFC 5802, mutual auth)
        self.username = username
        self.password = password
        if sasl_mechanism.upper() not in ("PLAIN", "SCRAM-SHA-256"):
            raise ValueError(f"unsupported SASL mechanism "
                             f"{sasl_mechanism!r}")
        self.sasl_mechanism = sasl_mechanism.upper()
        #: TLS: wrap every (re)connection before the first frame
        #: (``security.protocol=SSL``/SASL_SSL client side)
        self.ssl_context = ssl_context
        self._sock: Optional[socket.socket] = None
        self._corr = 0
        self._lock = threading.Lock()

    def _raw_call(self, s: socket.socket, api_key: int, api_version: int,
                  body: bytes) -> _Reader:
        """One request/response on an explicit socket — the single copy of
        the frame-build/send/recv protocol IO (``_call`` layers locking and
        connection lifecycle on top; the SASL exchange uses it directly
        before ``self._sock`` is published).  Verifies the correlation id."""
        self._corr += 1
        corr = self._corr
        frame = (_Writer().int16(api_key).int16(api_version)
                 .int32(corr).string(self.client_id).raw(body).done())
        s.sendall(struct.pack(">i", len(frame)) + frame)
        hdr = KafkaWireBroker._recv_exact(s, 4)
        if hdr is None:
            raise OSError("broker closed connection")
        (size,) = struct.unpack(">i", hdr)
        resp = KafkaWireBroker._recv_exact(s, size)
        if resp is None:
            raise OSError("broker closed connection")
        r = _Reader(resp)
        got = r.int32()
        if got != corr:
            raise ValueError(f"correlation mismatch {got} != {corr}")
        return r

    def _sasl_authenticate(self, s: socket.socket) -> None:
        mech = self.sasl_mechanism
        r = self._raw_call(s, _API_SASL_HANDSHAKE, 1,
                           _Writer().string(mech).done())
        err = r.int16()
        if err != _ERR_NONE:
            raise KafkaError(f"SASL handshake rejected (error {err})")

        def auth_round(token: bytes) -> bytes:
            rr = self._raw_call(s, _API_SASL_AUTHENTICATE, 0,
                                _Writer().bytes_(token).done())
            e = rr.int16()
            msg = rr.string()
            if e != _ERR_NONE:
                raise KafkaError(msg or f"SASL authentication failed "
                                        f"(error {e})")
            return rr.bytes_() or b""

        if mech == "PLAIN":
            auth_round(b"\0" + self.username.encode() + b"\0"
                       + self.password.encode())
            return
        # SCRAM-SHA-256: two token rounds + server-signature verification
        from flink_tpu.security.scram import ScramClient
        sc = ScramClient(self.username, self.password)
        server_first = auth_round(sc.first().encode()).decode()
        server_final = auth_round(sc.final(server_first).encode()).decode()
        sc.verify(server_final)

    def _conn(self) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection((self.host, self.port),
                                         timeout=self.timeout_s)
            try:
                if self.ssl_context is not None:
                    s = self.ssl_context.wrap_socket(
                        s, server_hostname=self.host)
                if self.username is not None:
                    self._sasl_authenticate(s)
            except BaseException:
                s.close()
                raise
            self._sock = s
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        with self._lock:
            s = self._conn()
            try:
                return self._raw_call(s, api_key, api_version, body)
            except OSError:
                self.close()
                raise

    def api_versions(self) -> List[Tuple[int, int, int]]:
        r = self._call(_API_VERSIONS, 0, b"")
        err = r.int16()
        if err:
            raise ValueError(f"ApiVersions error {err}")
        return r.array(lambda r: (r.int16(), r.int16(), r.int16()))

    def metadata(self, topics: Optional[List[str]] = None) -> Dict[str, Any]:
        body = _Writer().array(topics or [],
                               lambda w, t: w.string(t)).done()
        r = self._call(_API_METADATA, 0, body)
        brokers = r.array(lambda r: {"node_id": r.int32(),
                                     "host": r.string(),
                                     "port": r.int32()})

        def topic(r):
            err = r.int16()
            name = r.string()
            parts = r.array(lambda r: {
                "error": r.int16(), "partition": r.int32(),
                "leader": r.int32(),
                "replicas": r.array(lambda r: r.int32()),
                "isr": r.array(lambda r: r.int32())})
            return {"error": err, "name": name, "partitions": parts}

        return {"brokers": brokers, "topics": r.array(topic)}

    def produce(self, topic: str, partition: int,
                entries: List[Tuple[Optional[bytes], Optional[bytes]]]
                ) -> int:
        """Append (key, value) messages; returns the assigned base offset."""
        mset = encode_message_set([(0, k, v) for k, v in entries])
        body = (_Writer().int16(-1).int32(10_000)
                .array([(topic, [(partition, mset)])],
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, p: w.int32(p[0]).bytes_(p[1])))
                .done())
        r = self._call(_API_PRODUCE, 0, body)
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()
                err = r.int16()
                base = r.int64()
                if err:
                    raise ValueError(f"produce error {err}")
                return base
        raise ValueError("empty produce response")

    # -- transactions (KIP-98 client side) ----------------------------------
    def init_producer_id(self, transactional_id: Optional[str],
                         timeout_ms: int = 60_000) -> Tuple[int, int]:
        """-> (producer_id, producer_epoch); re-initializing an existing
        transactional id bumps the epoch and fences the old producer."""
        body = (_Writer().string(transactional_id).int32(timeout_ms).done())
        r = self._call(_API_INIT_PRODUCER_ID, 0, body)
        r.int32()                               # throttle
        err = r.int16()
        pid, epoch = r.int64(), r.int16()
        if err:
            raise KafkaError(f"InitProducerId error {err}")
        return pid, epoch

    def add_partitions_to_txn(self, transactional_id: str, producer_id: int,
                              producer_epoch: int,
                              partitions: Dict[str, List[int]]) -> None:
        body = (_Writer().string(transactional_id).int64(producer_id)
                .int16(producer_epoch)
                .array(sorted(partitions.items()),
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, p: w.int32(p)))
                .done())
        r = self._call(_API_ADD_PARTITIONS_TO_TXN, 0, body)
        r.int32()                               # throttle
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()
                err = r.int16()
                if err:
                    raise KafkaError(f"AddPartitionsToTxn error {err}")

    def produce_txn(self, transactional_id: str, producer_id: int,
                    producer_epoch: int, topic: str, partition: int,
                    entries: List[Tuple[Optional[bytes], Optional[bytes]]],
                    timestamp_ms: int = 0) -> None:
        """Transactional produce (v3, magic-2 batch carrying the producer
        id/epoch + transactional attribute): records stay invisible until
        ``end_txn(commit=True)``."""
        from flink_tpu.connectors.kafka_v2 import encode_record_batch
        batch = encode_record_batch(
            0, [(timestamp_ms, k, v, []) for k, v in entries],
            producer_id=producer_id, producer_epoch=producer_epoch,
            transactional=True)
        body = (_Writer().string(transactional_id).int16(-1).int32(10_000)
                .array([(topic, [(partition, batch)])],
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, p: w.int32(p[0]).bytes_(p[1])))
                .done())
        r = self._call(_API_PRODUCE, 3, body)
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()
                err = r.int16()
                r.int64()                       # base offset (-1: staged)
                r.int64()                       # log_append_time
                if err:
                    raise KafkaError(f"transactional produce error {err}")

    def end_txn(self, transactional_id: str, producer_id: int,
                producer_epoch: int, commit: bool) -> None:
        body = (_Writer().string(transactional_id).int64(producer_id)
                .int16(producer_epoch).int8(1 if commit else 0).done())
        r = self._call(_API_END_TXN, 0, body)
        r.int32()                               # throttle
        err = r.int16()
        if err:
            raise KafkaError(f"EndTxn error {err}", code=err)

    def list_transactions(self) -> List[Tuple[str, int, int, str]]:
        """-> [(transactional_id, producer_id, epoch, state)] of every
        OPEN transaction (recovery enumeration, ListTransactions analog)."""
        r = self._call(_API_LIST_TRANSACTIONS, 0, b"")
        r.int32()                               # throttle
        err = r.int16()
        if err:
            raise KafkaError(f"ListTransactions error {err}")
        return r.array(lambda r: (r.string(), r.int64(), r.int16(),
                                  r.string()))

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20
              ) -> Tuple[List[Tuple[int, Optional[bytes], Optional[bytes]]],
                         int]:
        """-> (messages from ``offset``, high watermark)."""
        body = (_Writer().int32(-1).int32(100).int32(1)
                .array([(topic, [(partition, offset, max_bytes)])],
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, p: w.int32(p[0]).int64(p[1])
                           .int32(p[2])))
                .done())
        r = self._call(_API_FETCH, 0, body)
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()
                err = r.int16()
                hw = r.int64()
                mset = r.bytes_() or b""
                if err == _ERR_OFFSET_OUT_OF_RANGE:
                    raise IndexError(f"offset {offset} out of range (hw {hw})")
                if err:
                    raise ValueError(f"fetch error {err}")
                return decode_message_set(mset), hw
        raise ValueError("empty fetch response")

    def latest_offset(self, topic: str, partition: int) -> int:
        body = (_Writer().int32(-1)
                .array([(topic, [(partition, -1, 1)])],
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, p: w.int32(p[0]).int64(p[1])
                           .int32(p[2])))
                .done())
        r = self._call(_API_LIST_OFFSETS, 0, body)
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()
                err = r.int16()
                offs = r.array(lambda r: r.int64())
                if err:
                    raise ValueError(f"list_offsets error {err}")
                return offs[0] if offs else 0
        raise ValueError("empty list_offsets response")


# ---------------------------------------------------------------------------
# source/sink seams
# ---------------------------------------------------------------------------

class KafkaExactlyOnceSink(TwoPhaseCommitSink):
    """Exactly-once Kafka sink: transactional produce bound to checkpoints
    — the ``FlinkKafkaProducer.java:100`` two-phase commit.

    The checkpoint-bound lifecycle (one transactional id PER EPOCH,
    ``{sink_id}-s{subtask}-{epoch}``; ``snapshot_state`` pre-commits,
    ``notify_checkpoint_complete(N)`` commits the epochs staged for
    checkpoints <= N, ``restore_state`` replays staged commits
    idempotently and sweeps dangling transactions) lives in the reusable
    :class:`~flink_tpu.connectors.sinks.TwoPhaseCommitSink` base; this
    adapter binds it to the broker's KIP-98 machinery: InitProducerId /
    AddPartitionsToTxn / transactional produce / EndTxn, with the
    committed-tid set making commit replay idempotent and
    ListTransactions driving the dangling sweep.

    A transaction handle is ``(tid, pid, producer_epoch)``."""

    def __init__(self, host: str, port: int, topic: str,
                 key_column: Optional[str] = None, num_partitions: int = 1,
                 sink_id: str = "kafka-eos", buffer_rows: int = 4096):
        super().__init__(sink_id=sink_id, buffer_rows=buffer_rows)
        self.host, self.port = host, port
        self.topic = topic
        self.key_column = key_column
        self.num_partitions = num_partitions
        self._client: Optional[KafkaWireClient] = None

    def _cli(self) -> KafkaWireClient:
        if self._client is None:
            self._client = KafkaWireClient(self.host, self.port)
        return self._client

    def open(self, ctx) -> None:
        super().open(ctx)
        self._cli()

    # -- TwoPhaseCommitSink contract ----------------------------------------
    def begin_transaction(self, txn_name: str) -> Tuple[str, int, int]:
        pid, pepoch = self._cli().init_producer_id(txn_name)
        self._cli().add_partitions_to_txn(
            txn_name, pid, pepoch,
            {self.topic: list(range(self.num_partitions))})
        return (txn_name, pid, pepoch)

    def write_rows(self, handle, rows) -> None:
        import json
        tid, pid, pepoch = handle
        buf = []
        for r in rows:
            key = (None if self.key_column is None
                   else str(r[self.key_column]).encode())
            buf.append((key, json.dumps(r, default=_json_default).encode()))
        if self.num_partitions == 1 or self.key_column is None:
            # single partition, or keyless round-robin
            parts: Dict[int, List] = {}
            for i, kv in enumerate(buf):
                parts.setdefault(i % self.num_partitions, []).append(kv)
        else:
            from flink_tpu.core.keygroups import hash_keys
            keys = np.asarray([k for k, _v in buf], object)
            pn = np.abs(hash_keys(keys).astype(np.int64)) \
                % self.num_partitions
            parts = {}
            for i, kv in enumerate(buf):
                parts.setdefault(int(pn[i]), []).append(kv)
        for p, entries in sorted(parts.items()):
            self._cli().produce_txn(tid, pid, pepoch, self.topic, p,
                                    entries)

    def commit_transaction(self, handle) -> None:
        # strict: a first-time commit (notify / end_input) answered with
        # INVALID_TXN_STATE means the staged records are GONE (aborted
        # from under us / lost open txn) — that must raise, not read as
        # committed
        tid, pid, pepoch = handle
        self._cli().end_txn(tid, pid, pepoch, commit=True)

    def replay_commit(self, handle) -> None:
        tid, pid, pepoch = handle
        try:
            self._cli().end_txn(tid, pid, pepoch, commit=True)
        except KafkaError as e:
            if e.code != _ERR_INVALID_TXN_STATE:
                raise
            # the tid aged out of the broker's committed-tids retention
            # window: the commit already happened long ago — recovery
            # proceeds idempotently instead of wedging

    def abort_transaction(self, handle) -> None:
        tid, pid, pepoch = handle
        try:
            self._cli().end_txn(tid, pid, pepoch, commit=False)
        except (KafkaError, OSError):
            pass

    def sweep_dangling(self, committed) -> None:
        c = self._cli()
        committed_tids = {h[0] for h in committed}
        mine = f"{self.sink_id}-s{self._subtask_index}-"
        #: scale-down sweep (FlinkKafkaProducer's abort of removed
        #: subtasks' transactions): subtask 0 also aborts dangling
        #: pre-commits whose owner index no longer exists at the NEW
        #: parallelism — otherwise their staged state leaks at the broker
        #: forever (no surviving subtask would ever match their prefix).
        #: A rescale restore is covered separately: the rescale machinery
        #: UNIONS staged transactions onto subtask 0's member
        #: (TwoPhaseCommitSink.merge_snapshots), whose commit replay runs
        #: BEFORE this sweep — so the sweep only ever aborts genuinely
        #: post-checkpoint transactions.
        sweep_all = f"{self.sink_id}-s"
        for tid, pid, pepoch, _state in c.list_transactions():
            if not tid or tid in committed_tids:
                continue
            abort = tid.startswith(mine)
            if not abort and self._subtask_index == 0 \
                    and tid.startswith(sweep_all):
                idx_s = tid[len(sweep_all):].split("-", 1)[0]
                abort = idx_s.isdigit() and int(idx_s) >= self._parallelism
            if not abort:
                continue
            try:
                c.end_txn(tid, pid, pepoch, commit=False)
            except KafkaError:
                pass  # raced with another recovering instance

    def close(self) -> None:
        super().close()
        if self._client is not None:
            self._client.close()
            self._client = None


class KafkaWireSource:
    """Bounded source over the wire protocol: one split per partition,
    reading up to each partition's high watermark at job start (the
    ``KafkaSource`` bounded(latest) mode); rows decode from JSON values."""

    bounded = True

    def __init__(self, host: str, port: int, topic: str,
                 timestamp_column: Optional[str] = None,
                 batch_rows: int = 1024,
                 out_of_orderness_ms: Optional[int] = None,
                 value_decoder=None):
        self.host, self.port = host, port
        self.topic = topic
        self.timestamp_column = timestamp_column
        self.batch_rows = batch_rows
        #: optional ``bytes -> list[dict]`` record decoder replacing the
        #: default one-JSON-object-per-value decode — the
        #: DeserializationSchema seam; CDC envelope formats
        #: (``flink_tpu.formats.cdc.cdc_decoder``) plug in here and may
        #: emit several changelog rows per Kafka record
        self.value_decoder = value_decoder
        #: emit Watermark(max_ts - bound) while reading; None = no in-read
        #: watermarks (offset order is NOT timestamp order on real topics —
        #: an unbounded per-chunk max would drop valid records as late; the
        #: bounded end-of-input flush still fires everything)
        self.out_of_orderness_ms = out_of_orderness_ms

    def create_splits(self, parallelism: int):
        from flink_tpu.connectors.sources import SourceSplit

        c = KafkaWireClient(self.host, self.port)
        try:
            meta = c.metadata([self.topic])
            n_parts = len(meta["topics"][0]["partitions"]) or 1

            class _Split(SourceSplit):
                def split_id(_self) -> str:
                    return f"{self.topic}-{_self.index}"

                def read(_self):
                    return self._read_partition(_self.index)

            return [_Split(self, p, n_parts) for p in range(n_parts)]
        finally:
            c.close()

    def _read_partition(self, part: int) -> Iterator[Any]:
        import json

        from flink_tpu.core.batch import RecordBatch, Watermark

        c = KafkaWireClient(self.host, self.port)
        try:
            end = c.latest_offset(self.topic, part)
            offset = 0
            max_bytes = 1 << 20
            rows: List[dict] = []
            # per-GENERATOR watermark state (each split reader tracks its
            # own running max; a shared one would also get RESET by sibling
            # generators starting up).  In the cluster runtimes each split
            # is its own subtask, so downstream valves min-combine the
            # per-partition watermarks correctly; the LOCAL depth-first
            # executor funnels all splits into one valve channel (max),
            # so there out_of_orderness_ms must also cover cross-partition
            # event-time skew
            wm_state = {"max_ts": None}
            while offset < end:
                msgs, _hw = c.fetch(self.topic, part, offset,
                                    max_bytes=max_bytes)
                if not msgs:
                    # a message larger than max_bytes arrives truncated (a
                    # real v0 broker cuts mid-message): grow and retry —
                    # never silently drop the rest of the partition
                    if max_bytes >= 1 << 30:
                        raise ValueError(
                            f"{self.topic}[{part}] offset {offset}: message "
                            f"exceeds 1GiB fetch budget")
                    max_bytes <<= 2
                    continue
                for off, _k, v in msgs:
                    if off >= end:
                        break
                    offset = off + 1
                    if v is None:
                        continue         # tombstone: no row payload
                    if self.value_decoder is not None:
                        rows.extend(self.value_decoder(v))
                    else:
                        rows.append(json.loads(v.decode()))
                while len(rows) >= self.batch_rows:
                    chunk, rows = rows[:self.batch_rows], rows[self.batch_rows:]
                    yield from self._emit(chunk, RecordBatch, Watermark,
                                          wm_state)
            if rows:
                yield from self._emit(rows, RecordBatch, Watermark, wm_state)
        finally:
            c.close()

    def _emit(self, rows, RecordBatch, Watermark, wm_state=None):
        cols = {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
        if self.timestamp_column is not None:
            ts = np.asarray(cols[self.timestamp_column], np.int64)
            yield RecordBatch(cols, timestamps=ts)
            if self.out_of_orderness_ms is not None and wm_state is not None:
                cur = wm_state["max_ts"]
                nxt = int(ts.max())
                wm_state["max_ts"] = nxt if cur is None else max(cur, nxt)
                yield Watermark(wm_state["max_ts"]
                                - self.out_of_orderness_ms)
        else:
            yield RecordBatch(cols)


class KafkaWireSink:
    """At-least-once JSON sink over the wire protocol (rows produce on
    ``write_batch``; key column optional for partition routing)."""

    clone_per_subtask = True

    def __init__(self, host: str, port: int, topic: str,
                 key_column: Optional[str] = None, num_partitions: int = 1,
                 value_encoder=None):
        self.host, self.port = host, port
        self.topic = topic
        self.key_column = key_column
        self.num_partitions = num_partitions
        #: optional ``row dict -> bytes`` value encoder replacing the
        #: default JSON — the SerializationSchema seam (e.g. the
        #: Confluent Avro wire format, ``formats.registry``)
        self.value_encoder = value_encoder
        self._client: Optional[KafkaWireClient] = None
        self._rr = 0

    def _cli(self) -> KafkaWireClient:
        if self._client is None:
            self._client = KafkaWireClient(self.host, self.port)
        return self._client

    def open(self, ctx) -> None:
        self._cli()

    def _enc(self, row: dict) -> bytes:
        if self.value_encoder is not None:
            return self.value_encoder(row)
        return _json.dumps(row, default=_json_default).encode()

    def write_batch(self, batch) -> None:
        if not len(batch):
            return
        rows = batch.to_rows()
        if self.key_column is None:
            self._rr += 1
            part = self._rr % self.num_partitions
            self._cli().produce(self.topic, part,
                                [(None, self._enc(r)) for r in rows])
            return
        if self.num_partitions == 1:
            # single partition, but the KEY still matters downstream
            # (compaction, keyed re-ingest)
            self._cli().produce(self.topic, 0, [
                (str(r[self.key_column]).encode(), self._enc(r))
                for r in rows])
            return
        from flink_tpu.core.keygroups import hash_keys
        keys = np.asarray(batch.column(self.key_column))
        parts = np.abs(hash_keys(keys).astype(np.int64)) % self.num_partitions
        for p in np.unique(parts).tolist():
            sel = [r for r, m in zip(rows, parts == p) if m]
            self._cli().produce(self.topic, int(p), [
                (str(r[self.key_column]).encode(), self._enc(r))
                for r in sel])

    def flush(self) -> None:
        pass

    def close(self) -> None:
        if self._client is not None:
            self._client.close()


from flink_tpu.connectors.util import json_default as _json_default  # noqa: E402 — shared encoder


def _encode_batch_v2(base_offset, records):
    """v2 record-batch codec bridge (lazy: kafka_v2 imports this module)."""
    from flink_tpu.connectors.kafka_v2 import encode_record_batch
    return encode_record_batch(base_offset, records)


def _decode_batches_v2(data):
    from flink_tpu.connectors.kafka_v2 import decode_record_batches
    return decode_record_batches(data)


def _decode_mixed_log(data: bytes) -> List[Tuple[int, Optional[bytes],
                                                 Optional[bytes], int]]:
    """Decode an on-disk partition log that may interleave v0 message sets
    (pre-upgrade appends) and v2 record batches — byte 16 of each entry is
    the magic in BOTH layouts (v0: offset8+size4+crc4+magic; v2:
    baseOffset8+batchLength4+leaderEpoch4+magic), so each entry is sniffed
    individually."""
    out: List[Tuple[int, Optional[bytes], Optional[bytes], int]] = []
    pos = 0
    while len(data) - pos >= 17:
        (size,) = struct.unpack_from(">i", data, pos + 8)
        if data[pos + 16] == 2:
            # one v2 batch: 12-byte prelude + batchLength
            end = pos + 12 + size
            out.extend((off, k, v, ts) for off, ts, k, v, _h
                       in _decode_batches_v2(data[pos:end]))
        else:
            # one v0 message: offset8 + size4 + size bytes
            end = pos + 12 + size
            out.extend((off, k, v, -1) for off, k, v
                       in decode_message_set(data[pos:end]))
        if end <= pos:
            raise ValueError("malformed partition log")
        pos = end
    return out
