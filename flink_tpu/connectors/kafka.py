"""Kafka binary wire protocol: client + broker speaking the real dialect.

The reference's Kafka connector (``flink-connectors/flink-connector-kafka/
.../KafkaSource.java``) talks to brokers over Kafka's binary TCP protocol.
This module implements that protocol from first principles — the v0/v1 API
generation (the long-stable dialect every Kafka client library still
speaks for bootstrapping):

- **Framing**: int32 size prefix; request header ``api_key:int16,
  api_version:int16, correlation_id:int32, client_id:nullable-string``;
  response header ``correlation_id:int32``.
- **APIs**: ApiVersions(18) v0, Metadata(3) v0, Produce(0) v0,
  Fetch(1) v0, ListOffsets(2) v0.
- **Message set v0**: ``[offset:int64 size:int32 message]*`` with
  ``message = crc:uint32 magic:int8(0) attributes:int8 key:bytes
  value:bytes`` — CRC32 over magic..value, verified on both sides.

:class:`KafkaWireBroker` serves the dialect over per-partition in-memory
logs with optional directory persistence; :class:`KafkaWireClient`
produces/fetches against ANY broker speaking v0 (including real Kafka).
:class:`KafkaWireSource`/:class:`KafkaWireSink` adapt them to the
framework's source/sink seams.
"""

from __future__ import annotations

import os
import socket
import struct
import threading
import zlib
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# wire primitives
# ---------------------------------------------------------------------------


class _Writer:
    def __init__(self):
        self._parts: List[bytes] = []

    def int8(self, v):
        self._parts.append(struct.pack(">b", v))
        return self

    def int16(self, v):
        self._parts.append(struct.pack(">h", v))
        return self

    def int32(self, v):
        self._parts.append(struct.pack(">i", v))
        return self

    def int64(self, v):
        self._parts.append(struct.pack(">q", v))
        return self

    def uint32(self, v):
        self._parts.append(struct.pack(">I", v))
        return self

    def string(self, s: Optional[str]):
        if s is None:
            return self.int16(-1)
        b = s.encode()
        self.int16(len(b))
        self._parts.append(b)
        return self

    def bytes_(self, b: Optional[bytes]):
        if b is None:
            return self.int32(-1)
        self.int32(len(b))
        self._parts.append(b)
        return self

    def raw(self, b: bytes):
        self._parts.append(b)
        return self

    def array(self, items, fn):
        self.int32(len(items))
        for it in items:
            fn(self, it)
        return self

    def done(self) -> bytes:
        return b"".join(self._parts)


class _Reader:
    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0

    def _take(self, n: int) -> bytes:
        b = self.data[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("short kafka frame")
        self.pos += n
        return b

    def int8(self):
        return struct.unpack(">b", self._take(1))[0]

    def int16(self):
        return struct.unpack(">h", self._take(2))[0]

    def int32(self):
        return struct.unpack(">i", self._take(4))[0]

    def int64(self):
        return struct.unpack(">q", self._take(8))[0]

    def uint32(self):
        return struct.unpack(">I", self._take(4))[0]

    def string(self) -> Optional[str]:
        n = self.int16()
        return None if n < 0 else self._take(n).decode()

    def bytes_(self) -> Optional[bytes]:
        n = self.int32()
        return None if n < 0 else self._take(n)

    def array(self, fn) -> list:
        return [fn(self) for _ in range(self.int32())]


# ---------------------------------------------------------------------------
# message set v0
# ---------------------------------------------------------------------------

def encode_message_v0(key: Optional[bytes], value: Optional[bytes]) -> bytes:
    body = (_Writer().int8(0).int8(0)        # magic=0, attributes=0
            .bytes_(key).bytes_(value).done())
    return _Writer().uint32(zlib.crc32(body) & 0xFFFFFFFF).raw(body).done()


def encode_message_set(entries: List[Tuple[int, Optional[bytes],
                                           Optional[bytes]]]) -> bytes:
    w = _Writer()
    for offset, key, value in entries:
        msg = encode_message_v0(key, value)
        w.int64(offset).int32(len(msg)).raw(msg)
    return w.done()


def decode_message_set(data: bytes) -> List[Tuple[int, Optional[bytes],
                                                  Optional[bytes]]]:
    """[(offset, key, value)] — CRC-verified; a trailing partial message
    (the protocol allows brokers to cut a fetch mid-message) is skipped."""
    out = []
    r = _Reader(data)
    while len(data) - r.pos >= 12:
        offset = r.int64()
        size = r.int32()
        if len(data) - r.pos < size:
            break                               # partial trailing message
        msg = r._take(size)
        mr = _Reader(msg)
        crc = mr.uint32()
        body = msg[4:]
        if (zlib.crc32(body) & 0xFFFFFFFF) != crc:
            raise ValueError(f"kafka message CRC mismatch at offset {offset}")
        magic = mr.int8()
        if magic != 0:
            raise ValueError(f"unsupported message magic {magic}")
        mr.int8()                               # attributes (no compression)
        key = mr.bytes_()
        value = mr.bytes_()
        out.append((offset, key, value))
    return out


# error codes (the real protocol's)
_ERR_NONE = 0
_ERR_OFFSET_OUT_OF_RANGE = 1
_ERR_UNKNOWN_TOPIC = 3
_ERR_UNKNOWN = -1

_API_PRODUCE, _API_FETCH, _API_LIST_OFFSETS = 0, 1, 2
_API_METADATA, _API_VERSIONS = 3, 18


# ---------------------------------------------------------------------------
# broker
# ---------------------------------------------------------------------------

class KafkaWireBroker:
    """A broker speaking the Kafka v0 wire dialect over per-partition logs.

    Real Kafka client libraries can bootstrap against it (ApiVersions →
    Metadata → Produce/Fetch); the in-repo client exercises the same
    frames.  ``directory``: when set, partitions persist as framed
    message-set files and survive restarts."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 directory: Optional[str] = None, node_id: int = 0):
        self.directory = directory
        self.node_id = node_id
        if directory:
            os.makedirs(directory, exist_ok=True)
        self._lock = threading.Lock()
        #: topic -> partition -> list[(offset, key, value)]
        self._logs: Dict[str, List[List[Tuple[int, bytes, bytes]]]] = {}
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        name="kafka-broker", daemon=True)
        if directory:
            self._load()

    # -- persistence -------------------------------------------------------
    def _part_path(self, topic: str, part: int) -> str:
        import urllib.parse
        return os.path.join(self.directory,
                            f"{urllib.parse.quote(topic, safe='')}-{part}.log")

    def _load(self) -> None:
        import json
        import urllib.parse
        manifest = os.path.join(self.directory, "_topics.json")
        if os.path.exists(manifest):
            with open(manifest) as f:
                for topic, n in json.load(f).items():
                    self._logs.setdefault(topic, [])
                    while len(self._logs[topic]) < n:
                        self._logs[topic].append([])
        for name in sorted(os.listdir(self.directory)):
            if not name.endswith(".log"):
                continue
            stem = name[:-4]
            tq, _, p = stem.rpartition("-")
            if not tq or not p.isdigit():
                continue                 # not a partition log of ours
            topic = urllib.parse.unquote(tq)
            with open(os.path.join(self.directory, name), "rb") as f:
                entries = decode_message_set(f.read())
            parts = self._logs.setdefault(topic, [])
            while len(parts) <= int(p):
                parts.append([])
            parts[int(p)] = list(entries)

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            parts = self._logs.setdefault(topic, [])
            while len(parts) < partitions:
                parts.append([])
            self._persist_manifest_locked()

    def _persist_manifest_locked(self) -> None:
        """Topic/partition METADATA must survive restarts too — an empty
        partition that vanished would fail keyed producers with
        UNKNOWN_TOPIC after a restart."""
        if not self.directory:
            return
        import json
        tmp = os.path.join(self.directory, "_topics.json#tmp")
        with open(tmp, "w") as f:
            json.dump({t: len(p) for t, p in self._logs.items()}, f)
            f.flush()
            os.fsync(f.fileno())   # as durable as the logs it describes
        os.replace(tmp, os.path.join(self.directory, "_topics.json"))

    # -- lifecycle ---------------------------------------------------------
    def start(self) -> "KafkaWireBroker":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, conn: socket.socket) -> None:
        conn.settimeout(60)
        try:
            while not self._stop.is_set():
                hdr = self._recv_exact(conn, 4)
                if hdr is None:
                    return
                (size,) = struct.unpack(">i", hdr)
                frame = self._recv_exact(conn, size)
                if frame is None:
                    return
                resp = self._handle(frame)
                if resp is None:
                    return                      # unsupported request: close
                conn.sendall(struct.pack(">i", len(resp)) + resp)
        except (OSError, EOFError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    @staticmethod
    def _recv_exact(conn, n: int) -> Optional[bytes]:
        buf = b""
        while len(buf) < n:
            chunk = conn.recv(n - len(buf))
            if not chunk:
                return None
            buf += chunk
        return buf

    # -- request dispatch --------------------------------------------------
    def _handle(self, frame: bytes) -> Optional[bytes]:
        r = _Reader(frame)
        api_key = r.int16()
        api_version = r.int16()
        correlation = r.int32()
        r.string()                              # client_id
        w = _Writer().int32(correlation)
        if api_key == _API_VERSIONS:
            w.int16(_ERR_NONE).array(
                [(_API_PRODUCE, 0, 0), (_API_FETCH, 0, 0),
                 (_API_LIST_OFFSETS, 0, 0), (_API_METADATA, 0, 0),
                 (_API_VERSIONS, 0, 0)],
                lambda w, t: w.int16(t[0]).int16(t[1]).int16(t[2]))
        elif api_key == _API_METADATA:
            self._metadata(r, w)
        elif api_key == _API_PRODUCE and api_version == 0:
            self._produce(r, w)
        elif api_key == _API_FETCH and api_version == 0:
            self._fetch(r, w)
        elif api_key == _API_LIST_OFFSETS and api_version == 0:
            self._list_offsets(r, w)
        else:
            # unsupported api/version: close the connection, the v0-era
            # broker behavior — a clean client-side error, never a hang
            return None
        return w.done()

    def _metadata(self, r: _Reader, w: _Writer) -> None:
        want = r.array(lambda r: r.string())
        with self._lock:
            topics = sorted(self._logs) if not want else list(want)
            w.array([(self.node_id, self.host, self.port)],
                    lambda w, b: w.int32(b[0]).string(b[1]).int32(b[2]))

            def topic_meta(w, t):
                parts = self._logs.get(t)
                if parts is None:
                    w.int16(_ERR_UNKNOWN_TOPIC).string(t).int32(0)
                    return
                w.int16(_ERR_NONE).string(t)
                w.array(list(range(len(parts))),
                        lambda w, p: w.int16(_ERR_NONE).int32(p)
                        .int32(self.node_id)
                        .array([self.node_id], lambda w, x: w.int32(x))
                        .array([self.node_id], lambda w, x: w.int32(x)))

            w.array(topics, topic_meta)

    def _produce(self, r: _Reader, w: _Writer) -> None:
        r.int16()                               # required_acks
        r.int32()                               # timeout
        results = []
        for _ in range(r.int32()):
            topic = r.string()
            per_part = []
            for _ in range(r.int32()):
                part = r.int32()
                mset = r.bytes_() or b""
                try:
                    entries = decode_message_set(mset)
                except ValueError:
                    per_part.append((part, _ERR_UNKNOWN, -1))
                    continue
                with self._lock:
                    parts = self._logs.get(topic)
                    if parts is None or not 0 <= part < len(parts):
                        per_part.append((part, _ERR_UNKNOWN_TOPIC, -1))
                        continue
                    base = len(parts[part])
                    stored = [(base + i, k, v)
                              for i, (_o, k, v) in enumerate(entries)]
                    parts[part].extend(stored)
                    if self.directory:
                        with open(self._part_path(topic, part), "ab") as f:
                            f.write(encode_message_set(stored))
                            f.flush()
                            os.fsync(f.fileno())
                per_part.append((part, _ERR_NONE, base))
            results.append((topic, per_part))
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1]).int64(p[2])))

    def _fetch(self, r: _Reader, w: _Writer) -> None:
        r.int32()                               # replica_id
        r.int32()                               # max_wait
        r.int32()                               # min_bytes
        results = []
        for _ in range(r.int32()):
            topic = r.string()
            per_part = []
            for _ in range(r.int32()):
                part = r.int32()
                offset = r.int64()
                max_bytes = r.int32()
                with self._lock:
                    parts = self._logs.get(topic)
                    if parts is None or not 0 <= part < len(parts):
                        per_part.append((part, _ERR_UNKNOWN_TOPIC, -1, b""))
                        continue
                    log = parts[part]
                    hw = len(log)
                    if offset > hw or offset < 0:
                        per_part.append((part, _ERR_OFFSET_OUT_OF_RANGE,
                                         hw, b""))
                        continue
                    take, size = [], 0
                    for e in log[offset:]:
                        m = encode_message_set([e])   # encode ONCE
                        if take and size + len(m) > max_bytes:
                            break
                        take.append(m)
                        size += len(m)
                per_part.append((part, _ERR_NONE, hw, b"".join(take)))
            results.append((topic, per_part))
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1]).int64(p[2])
            .bytes_(p[3])))

    def _list_offsets(self, r: _Reader, w: _Writer) -> None:
        r.int32()                               # replica_id
        results = []
        for _ in range(r.int32()):
            topic = r.string()
            per_part = []
            for _ in range(r.int32()):
                part = r.int32()
                time_ms = r.int64()
                r.int32()                       # max_num_offsets
                with self._lock:
                    parts = self._logs.get(topic)
                    if parts is None or not 0 <= part < len(parts):
                        per_part.append((part, _ERR_UNKNOWN_TOPIC, []))
                        continue
                    hw = len(parts[part])
                # -1 = latest, -2 = earliest (the protocol's sentinels)
                per_part.append((part, _ERR_NONE,
                                 [hw] if time_ms == -1 else [0]))
            results.append((topic, per_part))
        w.array(results, lambda w, t: w.string(t[0]).array(
            t[1], lambda w, p: w.int32(p[0]).int16(p[1])
            .array(p[2], lambda w, o: w.int64(o))))


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

class KafkaWireClient:
    """Produce/fetch against any broker speaking the v0 dialect."""

    def __init__(self, host: str, port: int, client_id: str = "flink-tpu",
                 timeout_s: float = 30.0):
        self.host, self.port = host, port
        self.client_id = client_id
        self.timeout_s = timeout_s
        self._sock: Optional[socket.socket] = None
        self._corr = 0
        self._lock = threading.Lock()

    def _conn(self) -> socket.socket:
        if self._sock is None:
            self._sock = socket.create_connection((self.host, self.port),
                                                  timeout=self.timeout_s)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _call(self, api_key: int, api_version: int, body: bytes) -> _Reader:
        with self._lock:
            self._corr += 1
            corr = self._corr
            frame = (_Writer().int16(api_key).int16(api_version)
                     .int32(corr).string(self.client_id).raw(body).done())
            s = self._conn()
            try:
                s.sendall(struct.pack(">i", len(frame)) + frame)
                hdr = KafkaWireBroker._recv_exact(s, 4)
                if hdr is None:
                    raise OSError("broker closed connection")
                (size,) = struct.unpack(">i", hdr)
                resp = KafkaWireBroker._recv_exact(s, size)
            except OSError:
                self.close()
                raise
        if resp is None:
            raise OSError("short kafka response")
        r = _Reader(resp)
        got = r.int32()
        if got != corr:
            raise ValueError(f"correlation mismatch {got} != {corr}")
        return r

    def api_versions(self) -> List[Tuple[int, int, int]]:
        r = self._call(_API_VERSIONS, 0, b"")
        err = r.int16()
        if err:
            raise ValueError(f"ApiVersions error {err}")
        return r.array(lambda r: (r.int16(), r.int16(), r.int16()))

    def metadata(self, topics: Optional[List[str]] = None) -> Dict[str, Any]:
        body = _Writer().array(topics or [],
                               lambda w, t: w.string(t)).done()
        r = self._call(_API_METADATA, 0, body)
        brokers = r.array(lambda r: {"node_id": r.int32(),
                                     "host": r.string(),
                                     "port": r.int32()})

        def topic(r):
            err = r.int16()
            name = r.string()
            parts = r.array(lambda r: {
                "error": r.int16(), "partition": r.int32(),
                "leader": r.int32(),
                "replicas": r.array(lambda r: r.int32()),
                "isr": r.array(lambda r: r.int32())})
            return {"error": err, "name": name, "partitions": parts}

        return {"brokers": brokers, "topics": r.array(topic)}

    def produce(self, topic: str, partition: int,
                entries: List[Tuple[Optional[bytes], Optional[bytes]]]
                ) -> int:
        """Append (key, value) messages; returns the assigned base offset."""
        mset = encode_message_set([(0, k, v) for k, v in entries])
        body = (_Writer().int16(-1).int32(10_000)
                .array([(topic, [(partition, mset)])],
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, p: w.int32(p[0]).bytes_(p[1])))
                .done())
        r = self._call(_API_PRODUCE, 0, body)
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()
                err = r.int16()
                base = r.int64()
                if err:
                    raise ValueError(f"produce error {err}")
                return base
        raise ValueError("empty produce response")

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20
              ) -> Tuple[List[Tuple[int, Optional[bytes], Optional[bytes]]],
                         int]:
        """-> (messages from ``offset``, high watermark)."""
        body = (_Writer().int32(-1).int32(100).int32(1)
                .array([(topic, [(partition, offset, max_bytes)])],
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, p: w.int32(p[0]).int64(p[1])
                           .int32(p[2])))
                .done())
        r = self._call(_API_FETCH, 0, body)
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()
                err = r.int16()
                hw = r.int64()
                mset = r.bytes_() or b""
                if err == _ERR_OFFSET_OUT_OF_RANGE:
                    raise IndexError(f"offset {offset} out of range (hw {hw})")
                if err:
                    raise ValueError(f"fetch error {err}")
                return decode_message_set(mset), hw
        raise ValueError("empty fetch response")

    def latest_offset(self, topic: str, partition: int) -> int:
        body = (_Writer().int32(-1)
                .array([(topic, [(partition, -1, 1)])],
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, p: w.int32(p[0]).int64(p[1])
                           .int32(p[2])))
                .done())
        r = self._call(_API_LIST_OFFSETS, 0, body)
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()
                err = r.int16()
                offs = r.array(lambda r: r.int64())
                if err:
                    raise ValueError(f"list_offsets error {err}")
                return offs[0] if offs else 0
        raise ValueError("empty list_offsets response")


# ---------------------------------------------------------------------------
# source/sink seams
# ---------------------------------------------------------------------------

class KafkaWireSource:
    """Bounded source over the wire protocol: one split per partition,
    reading up to each partition's high watermark at job start (the
    ``KafkaSource`` bounded(latest) mode); rows decode from JSON values."""

    bounded = True

    def __init__(self, host: str, port: int, topic: str,
                 timestamp_column: Optional[str] = None,
                 batch_rows: int = 1024,
                 out_of_orderness_ms: Optional[int] = None):
        self.host, self.port = host, port
        self.topic = topic
        self.timestamp_column = timestamp_column
        self.batch_rows = batch_rows
        #: emit Watermark(max_ts - bound) while reading; None = no in-read
        #: watermarks (offset order is NOT timestamp order on real topics —
        #: an unbounded per-chunk max would drop valid records as late; the
        #: bounded end-of-input flush still fires everything)
        self.out_of_orderness_ms = out_of_orderness_ms

    def create_splits(self, parallelism: int):
        from flink_tpu.connectors.sources import SourceSplit

        c = KafkaWireClient(self.host, self.port)
        try:
            meta = c.metadata([self.topic])
            n_parts = len(meta["topics"][0]["partitions"]) or 1

            class _Split(SourceSplit):
                def split_id(_self) -> str:
                    return f"{self.topic}-{_self.index}"

                def read(_self):
                    return self._read_partition(_self.index)

            return [_Split(self, p, n_parts) for p in range(n_parts)]
        finally:
            c.close()

    def _read_partition(self, part: int) -> Iterator[Any]:
        import json

        from flink_tpu.core.batch import RecordBatch, Watermark

        c = KafkaWireClient(self.host, self.port)
        try:
            end = c.latest_offset(self.topic, part)
            offset = 0
            max_bytes = 1 << 20
            rows: List[dict] = []
            # per-GENERATOR watermark state (each split reader tracks its
            # own running max; a shared one would also get RESET by sibling
            # generators starting up).  In the cluster runtimes each split
            # is its own subtask, so downstream valves min-combine the
            # per-partition watermarks correctly; the LOCAL depth-first
            # executor funnels all splits into one valve channel (max),
            # so there out_of_orderness_ms must also cover cross-partition
            # event-time skew
            wm_state = {"max_ts": None}
            while offset < end:
                msgs, _hw = c.fetch(self.topic, part, offset,
                                    max_bytes=max_bytes)
                if not msgs:
                    # a message larger than max_bytes arrives truncated (a
                    # real v0 broker cuts mid-message): grow and retry —
                    # never silently drop the rest of the partition
                    if max_bytes >= 1 << 30:
                        raise ValueError(
                            f"{self.topic}[{part}] offset {offset}: message "
                            f"exceeds 1GiB fetch budget")
                    max_bytes <<= 2
                    continue
                for off, _k, v in msgs:
                    if off >= end:
                        break
                    offset = off + 1
                    if v is None:
                        continue         # tombstone: no row payload
                    rows.append(json.loads(v.decode()))
                while len(rows) >= self.batch_rows:
                    chunk, rows = rows[:self.batch_rows], rows[self.batch_rows:]
                    yield from self._emit(chunk, RecordBatch, Watermark,
                                          wm_state)
            if rows:
                yield from self._emit(rows, RecordBatch, Watermark, wm_state)
        finally:
            c.close()

    def _emit(self, rows, RecordBatch, Watermark, wm_state=None):
        cols = {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
        if self.timestamp_column is not None:
            ts = np.asarray(cols[self.timestamp_column], np.int64)
            yield RecordBatch(cols, timestamps=ts)
            if self.out_of_orderness_ms is not None and wm_state is not None:
                cur = wm_state["max_ts"]
                nxt = int(ts.max())
                wm_state["max_ts"] = nxt if cur is None else max(cur, nxt)
                yield Watermark(wm_state["max_ts"]
                                - self.out_of_orderness_ms)
        else:
            yield RecordBatch(cols)


class KafkaWireSink:
    """At-least-once JSON sink over the wire protocol (rows produce on
    ``write_batch``; key column optional for partition routing)."""

    clone_per_subtask = True

    def __init__(self, host: str, port: int, topic: str,
                 key_column: Optional[str] = None, num_partitions: int = 1):
        self.host, self.port = host, port
        self.topic = topic
        self.key_column = key_column
        self.num_partitions = num_partitions
        self._client: Optional[KafkaWireClient] = None
        self._rr = 0

    def _cli(self) -> KafkaWireClient:
        if self._client is None:
            self._client = KafkaWireClient(self.host, self.port)
        return self._client

    def open(self, ctx) -> None:
        self._cli()

    def write_batch(self, batch) -> None:
        import json

        if not len(batch):
            return
        rows = batch.to_rows()
        if self.key_column is None:
            self._rr += 1
            part = self._rr % self.num_partitions
            self._cli().produce(self.topic, part, [
                (None, json.dumps(r, default=_json_default).encode())
                for r in rows])
            return
        if self.num_partitions == 1:
            # single partition, but the KEY still matters downstream
            # (compaction, keyed re-ingest)
            self._cli().produce(self.topic, 0, [
                (str(r[self.key_column]).encode(),
                 json.dumps(r, default=_json_default).encode())
                for r in rows])
            return
        from flink_tpu.core.keygroups import hash_keys
        keys = np.asarray(batch.column(self.key_column))
        parts = np.abs(hash_keys(keys).astype(np.int64)) % self.num_partitions
        for p in np.unique(parts).tolist():
            sel = [r for r, m in zip(rows, parts == p) if m]
            self._cli().produce(self.topic, int(p), [
                (str(r[self.key_column]).encode(),
                 json.dumps(r, default=_json_default).encode())
                for r in sel])

    def flush(self) -> None:
        pass

    def close(self) -> None:
        if self._client is not None:
            self._client.close()


def _json_default(o):
    if isinstance(o, np.generic):
        return o.item()
    raise TypeError(type(o).__name__)
