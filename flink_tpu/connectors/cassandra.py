"""Cassandra connector: CQL binary protocol v4 server, client, and sink.

Analog of ``flink-connectors/flink-connector-cassandra``
(``CassandraSink`` / ``CassandraRowOutputFormat``): rows write as
INSERTs (at-least-once, flush-on-checkpoint; Cassandra upserts by
primary key, so deterministic keys make replays idempotent — the same
recipe the reference documents), and a bounded source scans a table.

The wire dialect is the real CQL native protocol v4 on both sides:
9-byte frame header (version/flags/stream/opcode/length), STARTUP →
READY handshake, QUERY with consistency + flags, RESULT kinds (VOID /
ROWS with global-table-spec metadata / SET_KEYSPACE), ERROR frames.
Values ride the v4 type codec for the types the connector uses
(bigint/int/double/float/boolean/varchar).  ``CqlServer`` keeps
keyspaces of primary-keyed tables and evaluates the CQL subset the
connector emits (CREATE KEYSPACE/TABLE, INSERT, SELECT with WHERE on
the partition key, USE); a conforming driver can complete the same
handshake and query cycle.
"""

from __future__ import annotations

import re
import socket
import struct
import threading
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

# opcodes
OP_ERROR, OP_STARTUP, OP_READY = 0x00, 0x01, 0x02
OP_OPTIONS, OP_SUPPORTED = 0x05, 0x06
OP_QUERY, OP_RESULT = 0x07, 0x08

RESULT_VOID, RESULT_ROWS, RESULT_SET_KEYSPACE = 0x0001, 0x0002, 0x0003

# CQL type ids (v4 option codes)
T_VARCHAR, T_BIGINT, T_BOOLEAN, T_DOUBLE, T_FLOAT, T_INT = \
    0x0D, 0x02, 0x04, 0x07, 0x08, 0x09

_CQL_TYPES = {
    "text": T_VARCHAR, "varchar": T_VARCHAR, "bigint": T_BIGINT,
    "boolean": T_BOOLEAN, "double": T_DOUBLE, "float": T_FLOAT,
    "int": T_INT,
}


class CassandraError(Exception):
    pass


# ---------------------------------------------------------------------------
# value codec (v4 [bytes] values)
# ---------------------------------------------------------------------------


def _enc_value(type_id: int, v: Any) -> Optional[bytes]:
    if v is None:
        return None
    if type_id == T_VARCHAR:
        return str(v).encode()
    if type_id == T_BIGINT:
        return struct.pack(">q", int(v))
    if type_id == T_INT:
        return struct.pack(">i", int(v))
    if type_id == T_DOUBLE:
        return struct.pack(">d", float(v))
    if type_id == T_FLOAT:
        return struct.pack(">f", float(v))
    if type_id == T_BOOLEAN:
        return b"\x01" if v else b"\x00"
    raise CassandraError(f"unsupported type id {type_id}")


def _dec_value(type_id: int, b: Optional[bytes]) -> Any:
    if b is None:
        return None
    if type_id == T_VARCHAR:
        return b.decode()
    if type_id == T_BIGINT:
        return struct.unpack(">q", b)[0]
    if type_id == T_INT:
        return struct.unpack(">i", b)[0]
    if type_id == T_DOUBLE:
        return struct.unpack(">d", b)[0]
    if type_id == T_FLOAT:
        return struct.unpack(">f", b)[0]
    if type_id == T_BOOLEAN:
        return b != b"\x00"
    raise CassandraError(f"unsupported type id {type_id}")


def _string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">H", len(b)) + b


def _long_string(s: str) -> bytes:
    b = s.encode()
    return struct.pack(">I", len(b)) + b


def _read_string(data: bytes, pos: int) -> Tuple[str, int]:
    (n,) = struct.unpack_from(">H", data, pos)
    return data[pos + 2:pos + 2 + n].decode(), pos + 2 + n


def _bytes_val(b: Optional[bytes]) -> bytes:
    if b is None:
        return struct.pack(">i", -1)
    return struct.pack(">i", len(b)) + b


def _frame(version: int, stream: int, opcode: int, body: bytes) -> bytes:
    return struct.pack(">BBhBI", version, 0, stream, opcode,
                       len(body)) + body


def _recv_exact(sock: socket.socket, n: int) -> Optional[bytes]:
    buf = b""
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return buf


def _read_frame(sock) -> Optional[Tuple[int, int, int, bytes]]:
    hdr = _recv_exact(sock, 9)
    if hdr is None:
        return None
    version, _flags, stream, opcode, length = struct.unpack(">BBhBI", hdr)
    body = _recv_exact(sock, length) if length else b""
    if length and body is None:
        return None
    return version, stream, opcode, body


# ---------------------------------------------------------------------------
# server
# ---------------------------------------------------------------------------


class _CqlTable:
    def __init__(self, columns: List[str], types: List[str], pkey: str):
        self.columns = columns
        self.types = types
        self.pkey = pkey
        self.rows: Dict[Any, List[Any]] = {}   # pk -> row values (UPSERT)

    def type_ids(self) -> List[int]:
        return [_CQL_TYPES[t] for t in self.types]


class CqlServer:
    """Single-node CQL v4 server: keyspaces of primary-keyed tables."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._lock = threading.Lock()
        self.keyspaces: Dict[str, Dict[str, _CqlTable]] = {}
        self._srv = socket.create_server((host, port))
        self.host, self.port = self._srv.getsockname()[:2]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept_loop,
                                        daemon=True, name="cql-server")
        self._thread.start()

    def close(self) -> None:
        self._stop.set()
        try:
            self._srv.close()
        except OSError:
            pass

    def _accept_loop(self) -> None:
        self._srv.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self._srv.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(target=self._serve, args=(conn,),
                             daemon=True).start()

    def _serve(self, sock: socket.socket) -> None:
        keyspace = [None]
        try:
            while True:
                fr = _read_frame(sock)
                if fr is None:
                    return
                version, stream, opcode, body = fr
                resp_v = version | 0x80           # response direction bit
                if opcode == OP_OPTIONS:
                    # string multimap of supported options
                    sup = struct.pack(">H", 1) + _string("CQL_VERSION") \
                        + struct.pack(">H", 1) + _string("3.4.4")
                    sock.sendall(_frame(resp_v, stream, OP_SUPPORTED, sup))
                elif opcode == OP_STARTUP:
                    sock.sendall(_frame(resp_v, stream, OP_READY, b""))
                elif opcode == OP_QUERY:
                    (qlen,) = struct.unpack_from(">I", body, 0)
                    cql = body[4:4 + qlen].decode()
                    try:
                        resp = self._execute(cql, keyspace)
                    except CassandraError as e:
                        err = struct.pack(">i", 0x2200) + _string(str(e))
                        sock.sendall(_frame(resp_v, stream, OP_ERROR, err))
                        continue
                    except (ValueError, KeyError, IndexError,
                            TypeError) as e:
                        # malformed literals/columns surface as a
                        # recoverable ERROR frame — the CONNECTION must
                        # survive a bad query, as real Cassandra's does
                        err = struct.pack(">i", 0x2000) \
                            + _string(str(e) or type(e).__name__)
                        sock.sendall(_frame(resp_v, stream, OP_ERROR, err))
                        continue
                    sock.sendall(_frame(resp_v, stream, OP_RESULT, resp))
                else:
                    err = struct.pack(">i", 0x000A) \
                        + _string(f"unsupported opcode {opcode}")
                    sock.sendall(_frame(resp_v, stream, OP_ERROR, err))
        except (OSError, struct.error):
            pass
        finally:
            try:
                sock.close()
            except OSError:
                pass

    # -- CQL evaluation -----------------------------------------------------
    def _execute(self, cql: str, keyspace: List[Optional[str]]) -> bytes:
        s = cql.strip().rstrip(";").strip()
        up = s.upper()
        if up.startswith("CREATE KEYSPACE"):
            m = re.match(r"CREATE\s+KEYSPACE\s+(IF\s+NOT\s+EXISTS\s+)?"
                         r"(\w+)", s, re.I)
            if not m:
                raise CassandraError("malformed CREATE KEYSPACE")
            with self._lock:
                self.keyspaces.setdefault(m.group(2).lower(), {})
            return struct.pack(">i", RESULT_VOID)
        if up.startswith("USE "):
            name = s[4:].strip().lower()
            with self._lock:
                if name not in self.keyspaces:
                    raise CassandraError(f"keyspace {name} does not exist")
            keyspace[0] = name
            return struct.pack(">i", RESULT_SET_KEYSPACE) + _string(name)
        if up.startswith("CREATE TABLE"):
            return self._create_table(s, keyspace)
        if up.startswith("INSERT"):
            return self._insert(s, keyspace)
        if up.startswith("SELECT"):
            return self._select(s, keyspace)
        raise CassandraError(f"unsupported statement: {s.split()[0]}")

    def _resolve(self, name: str, keyspace) -> Tuple[str, str]:
        if "." in name:
            ks, _, t = name.partition(".")
            return ks.lower(), t.lower()
        if keyspace[0] is None:
            raise CassandraError("no keyspace selected")
        return keyspace[0], name.lower()

    def _table(self, name: str, keyspace) -> _CqlTable:
        ks, t = self._resolve(name, keyspace)
        with self._lock:
            tbl = self.keyspaces.get(ks, {}).get(t)
        if tbl is None:
            raise CassandraError(f"table {ks}.{t} does not exist")
        return tbl

    def _create_table(self, s: str, keyspace) -> bytes:
        m = re.match(r"CREATE\s+TABLE\s+(IF\s+NOT\s+EXISTS\s+)?([\w.]+)"
                     r"\s*\((.*)\)$", s, re.I | re.S)
        if not m:
            raise CassandraError("malformed CREATE TABLE")
        ks, t = self._resolve(m.group(2), keyspace)
        cols, types, pkey = [], [], None
        for part in m.group(3).split(","):
            part = part.strip()
            pm = re.match(r"PRIMARY\s+KEY\s*\(\s*(\w+)\s*\)", part, re.I)
            if pm:
                pkey = pm.group(1).lower()
                continue
            cm = re.match(r"(\w+)\s+(\w+)(\s+PRIMARY\s+KEY)?$", part, re.I)
            if not cm:
                raise CassandraError(f"malformed column def {part!r}")
            cname, ctype = cm.group(1).lower(), cm.group(2).lower()
            if ctype not in _CQL_TYPES:
                raise CassandraError(f"unsupported type {ctype!r}")
            cols.append(cname)
            types.append(ctype)
            if cm.group(3):
                pkey = cname
        if pkey is None:
            raise CassandraError("a PRIMARY KEY is required")
        with self._lock:
            self.keyspaces.setdefault(ks, {})
            if t in self.keyspaces[ks]:
                if m.group(1):           # IF NOT EXISTS: keep the table
                    return struct.pack(">i", RESULT_VOID)
                # real Cassandra raises AlreadyExists — silently replacing
                # would wipe stored rows a restarted job depends on
                raise CassandraError(f"table {ks}.{t} already exists")
            self.keyspaces[ks][t] = _CqlTable(cols, types, pkey)
        return struct.pack(">i", RESULT_VOID)

    def _insert(self, s: str, keyspace) -> bytes:
        m = re.match(r"INSERT\s+INTO\s+([\w.]+)\s*\(([^)]*)\)\s*VALUES"
                     r"\s*\((.*)\)$", s, re.I | re.S)
        if not m:
            raise CassandraError("malformed INSERT")
        tbl = self._table(m.group(1), keyspace)
        cols = [c.strip().lower() for c in m.group(2).split(",")]
        vals = _split_csv(m.group(3))
        if len(cols) != len(vals):
            raise CassandraError("column/value count mismatch")
        asmap = {c: _parse_literal(v) for c, v in zip(cols, vals)}
        if tbl.pkey not in asmap:
            raise CassandraError(f"missing PRIMARY KEY {tbl.pkey}")
        row = [asmap.get(c) for c in tbl.columns]
        with self._lock:
            existing = tbl.rows.get(asmap[tbl.pkey])
            if existing is not None:     # Cassandra semantics: UPSERT
                row = [n if c in asmap else e
                       for c, n, e in zip(tbl.columns, row, existing)]
            tbl.rows[asmap[tbl.pkey]] = row
        return struct.pack(">i", RESULT_VOID)

    def _select(self, s: str, keyspace) -> bytes:
        m = re.match(r"SELECT\s+(.*?)\s+FROM\s+([\w.]+)"
                     r"(?:\s+WHERE\s+(\w+)\s*=\s*(.+?))?"
                     r"(?:\s+LIMIT\s+(\d+))?$", s, re.I | re.S)
        if not m:
            raise CassandraError("malformed SELECT")
        tbl = self._table(m.group(2), keyspace)
        proj = ([c.strip().lower() for c in m.group(1).split(",")]
                if m.group(1).strip() != "*" else list(tbl.columns))
        for c in proj:
            if c not in tbl.columns:
                raise CassandraError(f"unknown column {c}")
        with self._lock:
            rows = list(tbl.rows.values())
        if m.group(3):
            col = m.group(3).lower()
            want = _parse_literal(m.group(4).strip())
            at = tbl.columns.index(col)
            rows = [r for r in rows if r[at] == want]
        if m.group(5):
            rows = rows[:int(m.group(5))]
        ks, t = self._resolve(m.group(2), keyspace)
        idxs = [tbl.columns.index(c) for c in proj]
        tids = [tbl.type_ids()[i] for i in idxs]
        # ROWS result: flags(global table spec) col-count, ks/table,
        # per-col name+type, row count, values
        body = struct.pack(">i", RESULT_ROWS)
        body += struct.pack(">iI", 0x0001, len(proj))
        body += _string(ks) + _string(t)
        for c, tid in zip(proj, tids):
            body += _string(c) + struct.pack(">H", tid)
        body += struct.pack(">I", len(rows))
        for r in rows:
            for i, tid in zip(idxs, tids):
                body += _bytes_val(_enc_value(tid, r[i]))
        return body


def _split_csv(s: str) -> List[str]:
    """Split a VALUES list on commas outside single quotes."""
    out, cur, q = [], [], False
    for ch in s:
        if ch == "'":
            q = not q
            cur.append(ch)
        elif ch == "," and not q:
            out.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    if cur:
        out.append("".join(cur).strip())
    return out


def _parse_literal(tok: str) -> Any:
    if tok.startswith("'") and tok.endswith("'"):
        return tok[1:-1].replace("''", "'")
    low = tok.lower()
    if low in ("true", "false"):
        return low == "true"
    if low == "null":
        return None
    try:
        return int(tok)
    except ValueError:
        return float(tok)


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------


class CqlClient:
    """Minimal CQL v4 driver: STARTUP handshake + QUERY cycle."""

    VERSION = 0x04

    def __init__(self, host: str, port: int, timeout_s: float = 10.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout_s)
        self._stream = 0
        try:
            opts = struct.pack(">H", 1) + _string("CQL_VERSION") \
                + _string("3.4.4")
            self.sock.sendall(_frame(self.VERSION, 0, OP_STARTUP, opts))
            fr = _read_frame(self.sock)
            if fr is None or fr[2] != OP_READY:
                raise CassandraError(f"startup failed: {fr and fr[2]}")
        except BaseException:
            self.sock.close()
            raise

    def execute(self, cql: str
                ) -> Tuple[List[Tuple[str, int]], List[List[Any]]]:
        """-> (columns as (name, type id), rows); non-SELECT returns
        ([], [])."""
        self._stream = (self._stream + 1) % 32000
        body = _long_string(cql) + struct.pack(">HB", 0x0001, 0)  # ONE
        self.sock.sendall(_frame(self.VERSION, self._stream, OP_QUERY,
                                 body))
        fr = _read_frame(self.sock)
        if fr is None:
            raise CassandraError("connection closed")
        _v, _stream, opcode, rbody = fr
        if opcode == OP_ERROR:
            (code,) = struct.unpack_from(">i", rbody, 0)
            msg, _ = _read_string(rbody, 4)
            raise CassandraError(f"[{code:#06x}] {msg}")
        if opcode != OP_RESULT:
            raise CassandraError(f"unexpected opcode {opcode}")
        (kind,) = struct.unpack_from(">i", rbody, 0)
        if kind != RESULT_ROWS:
            return [], []
        pos = 4
        flags, ncols = struct.unpack_from(">iI", rbody, pos)
        pos += 8
        if flags & 0x0001:
            _ks, pos = _read_string(rbody, pos)
            _t, pos = _read_string(rbody, pos)
        cols: List[Tuple[str, int]] = []
        for _ in range(ncols):
            name, pos = _read_string(rbody, pos)
            (tid,) = struct.unpack_from(">H", rbody, pos)
            pos += 2
            cols.append((name, tid))
        (nrows,) = struct.unpack_from(">I", rbody, pos)
        pos += 4
        rows = []
        for _ in range(nrows):
            row = []
            for _name, tid in cols:
                (ln,) = struct.unpack_from(">i", rbody, pos)
                pos += 4
                if ln < 0:
                    row.append(None)
                else:
                    row.append(_dec_value(tid, rbody[pos:pos + ln]))
                    pos += ln
            rows.append(row)
        return cols, rows

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


# ---------------------------------------------------------------------------
# sink / source
# ---------------------------------------------------------------------------


def _cql_literal(v: Any) -> str:
    if v is None:
        return "null"
    if isinstance(v, (bool, np.bool_)):
        return "true" if v else "false"
    if isinstance(v, (int, np.integer)):
        return str(int(v))
    if isinstance(v, (float, np.floating)):
        return repr(float(v))
    return "'" + str(v).replace("'", "''") + "'"


class CassandraSink:
    """``CassandraSink`` analog: rows INSERT (= upsert by primary key)
    with flush-on-checkpoint — at-least-once, and effectively-once when
    the primary key is deterministic (replays overwrite themselves, the
    recipe the reference documents)."""

    clone_per_subtask = True

    def __init__(self, host: str, port: int, table: str,
                 columns: List[str], buffer_rows: int = 500):
        self.host, self.port = host, port
        self.table = table
        self.columns = list(columns)
        self.buffer_rows = buffer_rows
        self._client: Optional[CqlClient] = None
        self._buf: List[dict] = []

    def _cli(self) -> CqlClient:
        if self._client is None:
            self._client = CqlClient(self.host, self.port)
        return self._client

    def open(self, ctx) -> None:
        self._cli()

    def write_batch(self, batch) -> None:
        self._buf.extend(batch.to_rows())
        if len(self._buf) >= self.buffer_rows:
            self._flush()

    def _flush(self) -> None:
        if not self._buf:
            return
        c = self._cli()
        for r in self._buf:
            cols = ", ".join(self.columns)
            vals = ", ".join(_cql_literal(r.get(col))
                             for col in self.columns)
            c.execute(f"INSERT INTO {self.table} ({cols}) "
                      f"VALUES ({vals})")
        self._buf = []

    def snapshot_state(self) -> Dict[str, Any]:
        self._flush()               # flush-on-checkpoint
        return {}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._buf = []

    def end_input(self) -> None:
        self._flush()

    def close(self) -> None:
        try:
            self._flush()
        except (CassandraError, OSError):
            pass
        if self._client is not None:
            self._client.close()
            self._client = None


class CassandraSource:
    """Bounded full-table scan (``CassandraInputFormat`` analog)."""

    bounded = True

    def __init__(self, host: str, port: int, table: str,
                 batch_rows: int = 4096,
                 timestamp_column: Optional[str] = None):
        self.host, self.port = host, port
        self.table = table
        self.batch_rows = batch_rows
        self.timestamp_column = timestamp_column

    def create_splits(self, parallelism: int):
        from flink_tpu.connectors.sources import SourceSplit

        src = self

        class _Split(SourceSplit):
            def split_id(_self) -> str:
                return f"{src.table}-0"

            def read(_self):
                return src._scan()

        return [_Split(self, 0, 1)]

    def _scan(self):
        from flink_tpu.connectors.util import rows_to_batch

        c = CqlClient(self.host, self.port)
        try:
            cols, rows = c.execute(f"SELECT * FROM {self.table}")
            names = [n for n, _t in cols]
            for lo in range(0, len(rows), self.batch_rows):
                chunk = [dict(zip(names, r))
                         for r in rows[lo:lo + self.batch_rows]]
                yield rows_to_batch(chunk, self.timestamp_column)
        finally:
            c.close()
