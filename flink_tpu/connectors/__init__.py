from flink_tpu.connectors.sinks import CollectSink, FunctionSink, PrintSink, Sink
from flink_tpu.connectors.sources import (
    CollectionSource,
    GeneratorSource,
    IteratorSource,
    SocketTextSource,
    Source,
    SourceSplit,
)
from flink_tpu.connectors.postgres import (
    PostgresSink,
    PostgresSource,
    PostgresWireClient,
    PostgresWireServer,
)

__all__ = [
    "CollectSink", "FunctionSink", "PrintSink", "Sink",
    "CollectionSource", "GeneratorSource", "IteratorSource",
    "SocketTextSource", "Source", "SourceSplit",
    "PostgresSink", "PostgresSource", "PostgresWireClient",
    "PostgresWireServer",
]
