"""Shared connector helpers: JSON row encoding and rows→RecordBatch
assembly (one implementation instead of per-connector copies)."""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np


def json_default(o):
    """``json.dumps(default=...)`` hook for numpy scalars/arrays — the
    ONE implementation (formats/__init__.py and the Kafka sinks alias
    it)."""
    if isinstance(o, np.generic):
        return o.item()
    if isinstance(o, np.ndarray):
        return o.tolist()
    raise TypeError(f"not JSON serializable: {type(o)}")


def rows_to_batch(rows: List[dict],
                  timestamp_column: Optional[str] = None):
    """Row dicts → RecordBatch with typed columns: the column set is the
    UNION over all rows (sparse fields fill with None → object dtype),
    numeric columns come out int64/float64, mixed-type columns fall back
    to object (never silent string coercion)."""
    from flink_tpu.core.batch import RecordBatch
    from flink_tpu.formats import _coerce_columns

    cols = _coerce_columns(rows)
    ts = (np.asarray(cols[timestamp_column], np.int64)
          if timestamp_column and timestamp_column in cols else None)
    return RecordBatch(cols, timestamps=ts)
