"""Sinks: batched record consumers (``SinkFunction`` analogs)."""

from __future__ import annotations

import sys
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.core.batch import RecordBatch


class Sink:
    def write_batch(self, batch: RecordBatch) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class CollectSink(Sink):
    """Gathers all batches in memory (``CollectSink.java`` /
    ``DataStream.executeAndCollect`` analog) — the test workhorse."""

    def __init__(self):
        import threading

        self.batches: List[RecordBatch] = []
        #: ONE CollectSink instance is shared by every parallel subtask
        #: (that is how collect() aggregates results), so appends from task
        #: threads race with another subtask's multi-step snapshot
        #: consolidation — serialize them
        self._lock = threading.Lock()

    def write_batch(self, batch: RecordBatch) -> None:
        with self._lock:
            self.batches.append(batch)

    def rows(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        with self._lock:
            batches = list(self.batches)
        for b in batches:
            cols = {k: np.asarray(v) for k, v in b.columns.items()}
            for i in range(len(b)):
                row = {k: (v[i].item() if isinstance(v[i], np.generic) else v[i])
                       for k, v in cols.items()}
                if b.timestamps is not None:
                    row["__ts__"] = int(np.asarray(b.timestamps)[i])
                out.append(row)
        return out

    def column(self, name: str) -> np.ndarray:
        with self._lock:
            batches = list(self.batches)
        parts = [np.asarray(b.column(name)) for b in batches if len(b)]
        return np.concatenate(parts) if parts else np.asarray([])

    # collected rows are operator STATE: a recovery that replays the source
    # from the last checkpoint must not lose rows collected before it
    # (exactly-once for the collect path, not just for aggregates).  Each
    # snapshot carries the FULL history — O(collected rows) per checkpoint,
    # inherent to a stateful collect (and why collect() is a test/debug
    # sink, not a production one); batches are consolidated first so the
    # payload is a few large arrays, and the incremental checkpoint layer's
    # content-hash dedup skips re-uploading unchanged chunks.
    def snapshot_state(self) -> Dict[str, Any]:
        with self._lock:
            self._consolidate_locked()
            return {"batches": [
                ({k: np.asarray(v) for k, v in b.columns.items()},
                 None if b.timestamps is None else np.asarray(b.timestamps))
                for b in self.batches]}

    def _consolidate_locked(self) -> None:
        """Merge buffered batches into one (columns + timestamps only —
        key-group metadata varies between restored and live batches and is
        irrelevant to a terminal sink).  Skipped when schemas differ.
        Caller holds the lock."""
        if len(self.batches) <= 1:
            return
        keys = set(self.batches[0].columns)
        has_ts = self.batches[0].timestamps is not None
        for b in self.batches[1:]:
            if set(b.columns) != keys or (b.timestamps is not None) != has_ts:
                return
        cols = {k: np.concatenate([np.asarray(b.columns[k])
                                   for b in self.batches]) for k in keys}
        ts = (np.concatenate([np.asarray(b.timestamps)
                              for b in self.batches]) if has_ts else None)
        self.batches = [RecordBatch(cols, timestamps=ts)]

    def restore_state(self, snap: Dict[str, Any]) -> None:
        with self._lock:
            self.batches = [RecordBatch(cols, timestamps=ts)
                            for cols, ts in snap.get("batches", [])]


class PrintSink(Sink):
    """``print()`` analog: one line per row to stdout/stderr."""

    def __init__(self, prefix: str = "", to_stderr: bool = False, limit: int = 0):
        self.prefix = prefix
        self.stream = sys.stderr if to_stderr else sys.stdout
        self.limit = limit
        self._printed = 0

    def write_batch(self, batch: RecordBatch) -> None:
        cols = {k: np.asarray(v) for k, v in batch.columns.items()}
        for i in range(len(batch)):
            if self.limit and self._printed >= self.limit:
                return
            row = {k: v[i] for k, v in cols.items()}
            p = f"{self.prefix}> " if self.prefix else ""
            print(f"{p}{row}", file=self.stream)
            self._printed += 1


class FunctionSink(Sink):
    """Adapts a plain callable(batch) -> None."""

    def __init__(self, fn: Callable[[RecordBatch], None]):
        self.fn = fn

    def write_batch(self, batch: RecordBatch) -> None:
        self.fn(batch)
