"""Sinks: batched record consumers (``SinkFunction`` analogs)."""

from __future__ import annotations

import sys
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.core.batch import RecordBatch


class Sink:
    def write_batch(self, batch: RecordBatch) -> None:
        raise NotImplementedError

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class CollectSink(Sink):
    """Gathers all batches in memory (``CollectSink.java`` /
    ``DataStream.executeAndCollect`` analog) — the test workhorse."""

    def __init__(self):
        import threading

        self.batches: List[RecordBatch] = []
        #: ONE CollectSink instance is shared by every parallel subtask
        #: (that is how collect() aggregates results), so appends from task
        #: threads race with another subtask's multi-step snapshot
        #: consolidation — serialize them
        self._lock = threading.Lock()

    def write_batch(self, batch: RecordBatch) -> None:
        with self._lock:
            self.batches.append(batch)

    def rows(self) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        with self._lock:
            batches = list(self.batches)
        for b in batches:
            cols = {k: np.asarray(v) for k, v in b.columns.items()}
            for i in range(len(b)):
                row = {k: (v[i].item() if isinstance(v[i], np.generic) else v[i])
                       for k, v in cols.items()}
                if b.timestamps is not None:
                    row["__ts__"] = int(np.asarray(b.timestamps)[i])
                out.append(row)
        return out

    def column(self, name: str) -> np.ndarray:
        with self._lock:
            batches = list(self.batches)
        parts = [np.asarray(b.column(name)) for b in batches if len(b)]
        return np.concatenate(parts) if parts else np.asarray([])

    # collected rows are operator STATE: a recovery that replays the source
    # from the last checkpoint must not lose rows collected before it
    # (exactly-once for the collect path, not just for aggregates).  Each
    # snapshot carries the FULL history — O(collected rows) per checkpoint,
    # inherent to a stateful collect (and why collect() is a test/debug
    # sink, not a production one); batches are consolidated first so the
    # payload is a few large arrays, and the incremental checkpoint layer's
    # content-hash dedup skips re-uploading unchanged chunks.
    def snapshot_state(self) -> Dict[str, Any]:
        with self._lock:
            self._consolidate_locked()
            return {"batches": [
                ({k: np.asarray(v) for k, v in b.columns.items()},
                 None if b.timestamps is None else np.asarray(b.timestamps))
                for b in self.batches]}

    def _consolidate_locked(self) -> None:
        """Merge buffered batches into one (columns + timestamps only —
        key-group metadata varies between restored and live batches and is
        irrelevant to a terminal sink).  Skipped when schemas differ.
        Caller holds the lock."""
        if len(self.batches) <= 1:
            return
        keys = set(self.batches[0].columns)
        has_ts = self.batches[0].timestamps is not None
        for b in self.batches[1:]:
            if set(b.columns) != keys or (b.timestamps is not None) != has_ts:
                return
        cols = {k: np.concatenate([np.asarray(b.columns[k])
                                   for b in self.batches]) for k in keys}
        ts = (np.concatenate([np.asarray(b.timestamps)
                              for b in self.batches]) if has_ts else None)
        self.batches = [RecordBatch(cols, timestamps=ts)]

    def restore_state(self, snap: Dict[str, Any]) -> None:
        with self._lock:
            self.batches = [RecordBatch(cols, timestamps=ts)
                            for cols, ts in snap.get("batches", [])]


class TwoPhaseCommitSink(Sink):
    """Checkpoint-bound two-phase-commit sink base — the
    ``TwoPhaseCommitSinkFunction.java`` skeleton, factored out of the
    Kafka exactly-once sink so ANY transactional backend gets the same
    lifecycle (the scenario suite's EOS sinks all ride this).

    One transaction PER EPOCH (``{sink_id}-s{subtask}-{epoch}``): rows
    buffer locally and flush into the epoch's transaction;
    ``snapshot_state`` PRE-COMMITS (flushes + ``pre_commit``; the
    transaction stays open at the backend, recorded with its checkpoint
    id); ``notify_checkpoint_complete(N)`` commits exactly the epochs
    staged for checkpoints <= N; ``restore_state`` replays the
    snapshot's staged commits (``commit_transaction`` MUST be idempotent
    under replay) and then ``sweep_dangling`` aborts this sink's other
    leftover transactions — a crash between pre-commit and commit
    neither loses (restore commits) nor duplicates (replayed commits are
    idempotent; post-checkpoint epochs abort).

    Subclass contract (a transaction *handle* is a tuple, JSON/pickle
    round-trippable — it rides checkpoint snapshots):

    - ``begin_transaction(txn_name) -> handle``
    - ``write_rows(handle, rows)`` — stage rows in the open transaction
    - ``pre_commit(handle)`` — durably stage (default no-op: backends
      like Kafka stage on every produce)
    - ``commit_transaction(handle)`` — MUST tolerate replay of an
      already-committed handle
    - ``abort_transaction(handle)``
    - ``sweep_dangling(committed_handles)`` — abort leftover open
      transactions of this sink (default no-op)
    """

    clone_per_subtask = True

    def __init__(self, sink_id: str = "2pc-sink", buffer_rows: int = 4096):
        self.sink_id = sink_id
        self.buffer_rows = max(1, int(buffer_rows))
        self._subtask_index = 0
        self._parallelism = 1
        self._epoch = 0
        self._handle: Optional[tuple] = None
        #: pre-committed transactions awaiting their checkpoint's
        #: completion: [(handle, checkpoint_id)]
        self._staged: List[tuple] = []
        self._rows: List[Dict[str, Any]] = []
        #: coordinator-HA fence (ISSUE-20): once a new leader restores this
        #: sink it raises ``fence_epoch`` to its leader epoch, after which a
        #: completion notification stamped with an OLDER epoch (a zombie
        #: ex-leader racing its last notify round) is rejected instead of
        #: committed — the staged transaction stays for the rightful
        #: leader's replay.  None (the default) disables the fence;
        #: un-stamped notifications (epoch=None) are always accepted for
        #: single-coordinator back-compat.
        self.fence_epoch: Optional[int] = None
        self.fenced_commits = 0

    # -- subclass contract ---------------------------------------------------
    def begin_transaction(self, txn_name: str) -> tuple:
        raise NotImplementedError

    def write_rows(self, handle: tuple, rows: List[Dict[str, Any]]) -> None:
        raise NotImplementedError

    def pre_commit(self, handle: tuple) -> None:
        pass

    def commit_transaction(self, handle: tuple) -> None:
        raise NotImplementedError

    def replay_commit(self, handle: tuple) -> None:
        """Commit during RESTORE replay: like :meth:`commit_transaction`
        but additionally tolerant of a transaction the backend no longer
        remembers because the commit happened long ago (e.g. a
        committed-id set aged past its retention) — recovery must
        proceed idempotently instead of wedging.  First-time commits
        (notify / end_input) stay STRICT: there an
        unknown-transaction answer means the staged rows are GONE, and
        treating it as committed would be silent loss."""
        self.commit_transaction(handle)

    def abort_transaction(self, handle: tuple) -> None:
        raise NotImplementedError

    def sweep_dangling(self, committed: List[tuple]) -> None:
        pass

    # -- lifecycle -----------------------------------------------------------
    def open(self, ctx) -> None:
        self._subtask_index = getattr(ctx, "subtask_index", 0)
        self._parallelism = max(1, getattr(ctx, "parallelism", 1) or 1)

    def txn_name(self, epoch: int) -> str:
        return f"{self.sink_id}-s{self._subtask_index}-{epoch}"

    def _current(self) -> tuple:
        if self._handle is None:
            self._handle = tuple(
                self.begin_transaction(self.txn_name(self._epoch)))
        return self._handle

    def write_batch(self, batch: RecordBatch) -> None:
        if not len(batch):
            return
        self._rows.extend(batch.to_rows())
        if len(self._rows) >= self.buffer_rows:
            self._flush()

    def _flush(self) -> None:
        if not self._rows:
            return
        self.write_rows(self._current(), self._rows)
        self._rows = []

    def snapshot_state(self) -> Dict[str, Any]:
        from flink_tpu.operators.base import current_checkpoint_id
        self._flush()
        if self._handle is not None:
            # pre-commit: the txn stays OPEN at the backend; only the
            # matching checkpoint's completion may commit it
            self.pre_commit(self._handle)
            self._staged.append((self._handle, current_checkpoint_id()))
            self._handle = None
            self._epoch += 1
        return {"epoch": self._epoch,
                #: marker field: the rescale machinery unions staged
                #: transactions across subtasks on it (merge_snapshots)
                "two_phase": self.sink_id,
                "staged": [tuple(h) + (cid,) for h, cid in self._staged]}

    def notify_checkpoint_complete(self, checkpoint_id: int,
                                   epoch: Optional[int] = None) -> None:
        if (self.fence_epoch is not None and epoch is not None
                and epoch < self.fence_epoch):
            # zombie leader's notify: commit NOTHING — the transactions it
            # wants committed belong to the new leader's restore replay
            self.fenced_commits += 1
            return
        keep = []
        for h, staged_for in self._staged:
            if staged_for is not None and staged_for > checkpoint_id:
                keep.append((h, staged_for))
                continue
            self.commit_transaction(h)
        self._staged = keep

    def end_input(self) -> None:
        # graceful end of stream: the tail epoch plus staged epochs whose
        # completion notification never arrived commit NOW (older epochs
        # first) — deferring to a final checkpoint's notify would lose
        # them on every bounded job in this runtime (no notify round is
        # guaranteed after end-of-input; reproduced as the scenario
        # suite's committed-output hole).  KNOWN WINDOW: end_input is
        # per-subtask, so a restart triggered by a SIBLING's failure
        # between this commit and the job's global finish replays this
        # subtask's post-last-checkpoint records into fresh transactions
        # — duplicates.  The window only opens when the restore
        # checkpoint predates this subtask's final snapshot (a completed
        # final checkpoint restores it as finished, which does not
        # re-run), and it is exactly the tail-commit exposure the Kafka
        # EOS sink always had — not widened by the staged replay here.
        self._flush()
        for h, _cid in self._staged:
            self.commit_transaction(h)
        self._staged = []
        if self._handle is not None:
            self.pre_commit(self._handle)
            self.commit_transaction(self._handle)
            self._handle = None
            self._epoch += 1

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._epoch = int(snap.get("epoch", 0))
        self._rows = []
        self._handle = None
        committed: List[tuple] = []
        for entry in snap.get("staged", []):
            h = tuple(entry[:-1])
            self.replay_commit(h)           # idempotent replay
            committed.append(h)
        self._staged = []
        self.sweep_dangling(committed)

    def close(self) -> None:
        if self._handle is not None:
            try:
                self.abort_transaction(self._handle)
            except Exception:  # noqa: BLE001 — best-effort on teardown
                pass
            self._handle = None

    # -- rescale -------------------------------------------------------------
    @staticmethod
    def _owner_of(entry: tuple) -> Optional[int]:
        """Owner subtask index parsed from a staged entry's transaction
        name (``{sink_id}-s{i}-{epoch}``, the :meth:`txn_name` scheme both
        built-in 2PC sinks use).  None when unparseable."""
        name = entry[0] if entry and isinstance(entry[0], str) else None
        if name is None or "-s" not in name:
            return None
        idx_s = name.rsplit("-s", 1)[1].split("-", 1)[0]
        return int(idx_s) if idx_s.isdigit() else None

    @staticmethod
    def split_snapshot(snap: Dict[str, Any], max_parallelism: int,
                       new_parallelism: int) -> List[Dict[str, Any]]:
        """Rescale split.  EVERY part keeps the (merged, max) ``epoch`` —
        a part restored with an empty ``{}`` would restart at epoch 0 and
        reuse transaction names that may still be staged-open at the
        backend (InitProducerId-style fencing would then DESTROY a
        pre-commit awaiting its replay).  Staged entries route to their
        OWNING subtask index when it survives the rescale (its own
        restore commits them BEFORE its dangling sweep runs — same
        thread, no cross-subtask race with the sweep's own-prefix
        aborts); entries of removed or unparseable owners park on part 0
        (committed before part 0's sweep, whose removed-index branch
        excludes its own committed list)."""
        parts = [dict(snap, staged=[]) for _ in range(new_parallelism)]
        for entry in snap.get("staged", []):
            owner = TwoPhaseCommitSink._owner_of(tuple(entry))
            idx = owner if (owner is not None
                            and 0 <= owner < new_parallelism) else 0
            parts[idx]["staged"].append(tuple(entry))
        return parts

    @staticmethod
    def merge_snapshots(snaps: List[Dict[str, Any]]) -> Dict[str, Any]:
        """Rescale union: EVERY part's pre-committed (staged) transactions
        ride to the merged member (kept by subtask 0) so the restore's
        idempotent commit replay covers removed and re-indexed subtasks.
        Keep-subtask-0 would strand pre-commits whose owner did not
        survive — if the pre-rescale cancel raced the cut's notify round,
        the stranded transaction is still OPEN at the backend and the new
        incarnation's dangling sweep would ABORT it: committed records
        lost.  ``epoch`` takes the max so subtask 0 can never reuse a
        transaction name that may still be open."""
        live = [s for s in snaps if isinstance(s, dict) and s]
        staged: List[tuple] = []
        seen = set()
        for s in live:
            for entry in s.get("staged", []):
                t = tuple(entry)
                if t not in seen:
                    seen.add(t)
                    staged.append(t)
        out = dict(live[0]) if live else {}
        out["staged"] = staged
        out["epoch"] = max((int(s.get("epoch", 0)) for s in live), default=0)
        return out


class PrintSink(Sink):
    """``print()`` analog: one line per row to stdout/stderr."""

    def __init__(self, prefix: str = "", to_stderr: bool = False, limit: int = 0):
        self.prefix = prefix
        self.stream = sys.stderr if to_stderr else sys.stdout
        self.limit = limit
        self._printed = 0

    def write_batch(self, batch: RecordBatch) -> None:
        cols = {k: np.asarray(v) for k, v in batch.columns.items()}
        for i in range(len(batch)):
            if self.limit and self._printed >= self.limit:
                return
            row = {k: v[i] for k, v in cols.items()}
            p = f"{self.prefix}> " if self.prefix else ""
            print(f"{p}{row}", file=self.stream)
            self._printed += 1


class FunctionSink(Sink):
    """Adapts a plain callable(batch) -> None."""

    def __init__(self, fn: Callable[[RecordBatch], None]):
        self.fn = fn

    def write_batch(self, batch: RecordBatch) -> None:
        self.fn(batch)
