"""Kafka modern protocol: v2 record batches + consumer-group coordination.

Extends the v0 wire dialect (:mod:`flink_tpu.connectors.kafka`) with the
format every broker of the last decade speaks — matching what the
reference's connector is built on
(``flink-connectors/flink-connector-kafka/src/main/java/org/apache/flink/
connector/kafka/source/KafkaSource.java:1``, reader/enumerator under
``source/``):

- **Record batch (magic 2)**: the ``baseOffset/batchLength/
  partitionLeaderEpoch/magic/crc/attributes/...`` header with **CRC32C**
  over attributes..end, followed by varint-delta records
  (``length, attributes, timestampDelta, offsetDelta, key, value,
  headers``) — all varints zigzag-encoded.
- **Group coordination APIs**: FindCoordinator(10), JoinGroup(11),
  Heartbeat(12), LeaveGroup(13), SyncGroup(14) with the consumer
  subscription/assignment embedded protocol, and committed offsets via
  OffsetCommit(8) v2 / OffsetFetch(9) v1.

:class:`KafkaGroupConsumer` runs the full client-side dance (join →
leader-side range assignment → sync → heartbeat → commit);
:class:`KafkaGroupSource` adapts it to the framework's source seam with
committed-offset restart.  The broker side lives in
:class:`~flink_tpu.connectors.kafka.KafkaWireBroker` (same listener, new
APIs).
"""

from __future__ import annotations

import struct
import threading
import time
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.connectors.kafka import (KafkaWireClient, _Reader, _Writer)
from flink_tpu.native import crc32c

# api keys (real protocol numbers)
API_OFFSET_COMMIT = 8
API_OFFSET_FETCH = 9
API_FIND_COORDINATOR = 10
API_JOIN_GROUP = 11
API_HEARTBEAT = 12
API_LEAVE_GROUP = 13
API_SYNC_GROUP = 14

# error codes (real protocol numbers)
ERR_NONE = 0
ERR_NOT_COORDINATOR = 16
ERR_ILLEGAL_GENERATION = 22
ERR_UNKNOWN_MEMBER_ID = 25
ERR_REBALANCE_IN_PROGRESS = 27


# ---------------------------------------------------------------------------
# varint (zigzag) — record-level integers in the v2 format
# ---------------------------------------------------------------------------

def _zz_enc(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _zz_dec(v: int) -> int:
    return (v >> 1) ^ -(v & 1)


def write_varint(out: bytearray, v: int) -> None:
    u = _zz_enc(v) & 0xFFFFFFFFFFFFFFFF
    while u >= 0x80:
        out.append((u & 0x7F) | 0x80)
        u >>= 7
    out.append(u)


def read_varint(data: bytes, pos: int) -> Tuple[int, int]:
    u = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        u |= (b & 0x7F) << shift
        if not b & 0x80:
            break
        shift += 7
        if shift > 63:
            raise ValueError("malformed varint")
    return _zz_dec(u), pos


# ---------------------------------------------------------------------------
# record batch v2 codec
# ---------------------------------------------------------------------------

#: (timestamp_ms, key|None, value|None, headers=[(str, bytes|None)])
Record = Tuple[int, Optional[bytes], Optional[bytes],
               List[Tuple[str, Optional[bytes]]]]


def encode_record_batch(base_offset: int, records: List[Record],
                        producer_id: int = -1, producer_epoch: int = -1,
                        transactional: bool = False) -> bytes:
    """One magic-2 batch.  CRC32C covers attributes..end (the bytes after
    the crc field), exactly as brokers verify it.  ``producer_id`` /
    ``producer_epoch`` + the transactional attribute bit (0x10) mark the
    batch as part of a transaction — the broker fences stale epochs with
    them (KIP-98 exactly-once produce)."""
    if not records:
        return b""
    base_ts = min(r[0] for r in records)
    max_ts = max(r[0] for r in records)
    recs = bytearray()
    for i, (ts, key, value, headers) in enumerate(records):
        body = bytearray()
        body.append(0)                               # record attributes
        write_varint(body, ts - base_ts)             # timestampDelta
        write_varint(body, i)                        # offsetDelta
        if key is None:
            write_varint(body, -1)
        else:
            write_varint(body, len(key))
            body += key
        if value is None:
            write_varint(body, -1)
        else:
            write_varint(body, len(value))
            body += value
        write_varint(body, len(headers))
        for hk, hv in headers:
            hkb = hk.encode()
            write_varint(body, len(hkb))
            body += hkb
            if hv is None:
                write_varint(body, -1)
            else:
                write_varint(body, len(hv))
                body += hv
        write_varint(recs, len(body))
        recs += body
    # attributes(2) lastOffsetDelta(4) baseTs(8) maxTs(8) producerId(8)
    # producerEpoch(2) baseSequence(4) recordCount(4)
    attrs = 0x10 if transactional else 0
    after_crc = struct.pack(">hiqqqhii", attrs, len(records) - 1, base_ts,
                            max_ts, producer_id, producer_epoch, -1,
                            len(records)) + bytes(recs)
    crc = crc32c(after_crc)
    # partitionLeaderEpoch(4) magic(1) crc(4) + after_crc
    batch_tail = struct.pack(">ibI", 0, 2, crc) + after_crc
    return struct.pack(">qi", base_offset, len(batch_tail)) + batch_tail


#: decoded record: (offset, timestamp_ms, key, value, headers)
DecodedRecord = Tuple[int, int, Optional[bytes], Optional[bytes],
                      List[Tuple[str, Optional[bytes]]]]


def batch_producer_info(data: bytes) -> Tuple[int, int, bool]:
    """(producer_id, producer_epoch, transactional) of the FIRST batch in
    ``data`` — the fencing fields a broker reads before accepting a
    transactional produce.  (-1, -1, False) when absent/short."""
    # header: baseOffset(8) batchLen(4) leaderEpoch(4) magic(1) crc(4)
    # attributes(2) lastOffsetDelta(4) baseTs(8) maxTs(8) producerId(8)
    # producerEpoch(2)
    if len(data) < 53:
        return -1, -1, False
    (attrs,) = struct.unpack_from(">h", data, 21)
    pid, epoch = struct.unpack_from(">qh", data, 43)
    return pid, epoch, bool(attrs & 0x10)


def decode_record_batches(data: bytes) -> List[DecodedRecord]:
    """Every complete batch in ``data`` (a trailing partial batch — legal in
    fetch responses — is skipped); CRC32C-verified."""
    out: List[DecodedRecord] = []
    pos = 0
    while len(data) - pos >= 12:
        base_offset, batch_len = struct.unpack_from(">qi", data, pos)
        if len(data) - pos - 12 < batch_len:
            break                                    # partial trailing batch
        tail = data[pos + 12: pos + 12 + batch_len]
        pos += 12 + batch_len
        _epoch, magic = struct.unpack_from(">ib", tail, 0)
        if magic != 2:
            raise ValueError(f"unsupported batch magic {magic}")
        (crc,) = struct.unpack_from(">I", tail, 5)
        after = tail[9:]
        if crc32c(after) != crc:
            raise ValueError(
                f"record batch CRC32C mismatch at offset {base_offset}")
        (_attrs, _last_delta, base_ts, _max_ts, _pid, _pepoch, _bseq,
         count) = struct.unpack_from(">hiqqqhii", after, 0)
        p = struct.calcsize(">hiqqqhii")
        for _ in range(count):
            rec_len, p = read_varint(after, p)
            rec_end = p + rec_len
            p += 1                                   # record attributes
            ts_delta, p = read_varint(after, p)
            off_delta, p = read_varint(after, p)
            klen, p = read_varint(after, p)
            key = None if klen < 0 else after[p:p + klen]
            p += max(klen, 0)
            vlen, p = read_varint(after, p)
            value = None if vlen < 0 else after[p:p + vlen]
            p += max(vlen, 0)
            nh, p = read_varint(after, p)
            headers: List[Tuple[str, Optional[bytes]]] = []
            for _h in range(nh):
                hklen, p = read_varint(after, p)
                hk = after[p:p + hklen].decode()
                p += hklen
                hvlen, p = read_varint(after, p)
                hv = None if hvlen < 0 else after[p:p + hvlen]
                p += max(hvlen, 0)
                headers.append((hk, hv))
            if p != rec_end:
                raise ValueError("record length mismatch")
            out.append((base_offset + off_delta, base_ts + ts_delta,
                        key, value, headers))
    return out


# ---------------------------------------------------------------------------
# consumer protocol (embedded subscription/assignment formats)
# ---------------------------------------------------------------------------

def encode_subscription(topics: List[str]) -> bytes:
    w = _Writer().int16(0)
    w.array(topics, lambda w, t: w.string(t))
    w.bytes_(None)
    return w.done()


def decode_subscription(data: bytes) -> List[str]:
    r = _Reader(data)
    r.int16()
    topics = r.array(lambda r: r.string())
    return topics


def encode_assignment(parts: Dict[str, List[int]]) -> bytes:
    w = _Writer().int16(0)
    w.array(sorted(parts.items()), lambda w, t: w.string(t[0]).array(
        t[1], lambda w, p: w.int32(p)))
    w.bytes_(None)
    return w.done()


def decode_assignment(data: bytes) -> Dict[str, List[int]]:
    r = _Reader(data)
    r.int16()
    out: Dict[str, List[int]] = {}
    for _ in range(r.int32()):
        topic = r.string()
        out[topic] = r.array(lambda r: r.int32())
    return out


def range_assign(members: List[Tuple[str, List[str]]],
                 partitions: Dict[str, int]) -> Dict[str, Dict[str, List[int]]]:
    """The client-side RangeAssignor the group LEADER runs: per topic,
    contiguous partition ranges to subscribed members in member-id order."""
    out: Dict[str, Dict[str, List[int]]] = {m: {} for m, _ in members}
    for topic, n_parts in sorted(partitions.items()):
        subs = sorted(m for m, topics in members if topic in topics)
        if not subs:
            continue
        per = n_parts // len(subs)
        extra = n_parts % len(subs)
        start = 0
        for i, m in enumerate(subs):
            take = per + (1 if i < extra else 0)
            if take:
                out[m][topic] = list(range(start, start + take))
            start += take
    return out


# ---------------------------------------------------------------------------
# group-aware client
# ---------------------------------------------------------------------------

class KafkaGroupConsumer:
    """The consumer-group dance against any coordinator speaking the group
    APIs: FindCoordinator → JoinGroup → (leader assigns) → SyncGroup →
    Heartbeat / OffsetCommit / OffsetFetch.  One instance = one member."""

    def __init__(self, host: str, port: int, group_id: str,
                 topics: List[str], client_id: str = "flink-tpu",
                 session_timeout_ms: int = 10_000):
        self.group_id = group_id
        self.topics = list(topics)
        self.session_timeout_ms = session_timeout_ms
        self.member_id = ""
        self.generation = -1
        self.assignment: Dict[str, List[int]] = {}
        self._cli = KafkaWireClient(host, port, client_id=client_id)

    # -- raw calls ----------------------------------------------------------
    def find_coordinator(self) -> Tuple[int, str, int]:
        body = _Writer().string(self.group_id).done()
        r = self._cli._call(API_FIND_COORDINATOR, 0, body)
        err = r.int16()
        if err:
            raise ValueError(f"FindCoordinator error {err}")
        return r.int32(), r.string(), r.int32()

    def _join(self) -> Tuple[int, List[Tuple[str, bytes]]]:
        sub = encode_subscription(self.topics)
        body = (_Writer().string(self.group_id)
                .int32(self.session_timeout_ms)
                .string(self.member_id).string("consumer")
                .array([("range", sub)],
                       lambda w, p: w.string(p[0]).bytes_(p[1]))
                .done())
        r = self._cli._call(API_JOIN_GROUP, 0, body)
        err = r.int16()
        if err == ERR_UNKNOWN_MEMBER_ID:
            self.member_id = ""
            raise _Rejoin()
        if err:
            raise ValueError(f"JoinGroup error {err}")
        self.generation = r.int32()
        r.string()                                   # protocol
        leader = r.string()
        self.member_id = r.string()
        members = r.array(lambda r: (r.string(), r.bytes_()))
        return (leader == self.member_id), members

    def _sync(self, assignments: Optional[Dict[str, bytes]]) -> bytes:
        items = sorted((assignments or {}).items())
        body = (_Writer().string(self.group_id).int32(self.generation)
                .string(self.member_id)
                .array(items, lambda w, p: w.string(p[0]).bytes_(p[1]))
                .done())
        r = self._cli._call(API_SYNC_GROUP, 0, body)
        err = r.int16()
        if err in (ERR_REBALANCE_IN_PROGRESS, ERR_ILLEGAL_GENERATION,
                   ERR_UNKNOWN_MEMBER_ID):
            raise _Rejoin()
        if err:
            raise ValueError(f"SyncGroup error {err}")
        return r.bytes_() or b""

    def join(self, max_attempts: int = 10) -> Dict[str, List[int]]:
        """Run the join+sync dance to a stable assignment."""
        for _ in range(max_attempts):
            try:
                is_leader, members = self._join()
                assignments = None
                if is_leader:
                    subs = [(m, decode_subscription(meta))
                            for m, meta in members]
                    n_parts = self._partition_counts()
                    plan = range_assign(subs, n_parts)
                    assignments = {m: encode_assignment(p)
                                   for m, p in plan.items()}
                mine = self._sync(assignments)
                self.assignment = decode_assignment(mine) if mine else {}
                return self.assignment
            except _Rejoin:
                time.sleep(0.05)
                continue
        raise TimeoutError("consumer group join did not stabilize")

    def _partition_counts(self) -> Dict[str, int]:
        meta = self._cli.metadata(self.topics)
        return {t["name"]: len(t["partitions"]) for t in meta["topics"]
                if t["error"] == 0}

    def heartbeat(self) -> bool:
        """True = stable; False = the group is rebalancing, call join()."""
        body = (_Writer().string(self.group_id).int32(self.generation)
                .string(self.member_id).done())
        r = self._cli._call(API_HEARTBEAT, 0, body)
        err = r.int16()
        if err == ERR_NONE:
            return True
        if err in (ERR_REBALANCE_IN_PROGRESS, ERR_ILLEGAL_GENERATION,
                   ERR_UNKNOWN_MEMBER_ID):
            if err == ERR_UNKNOWN_MEMBER_ID:
                self.member_id = ""
            return False
        raise ValueError(f"Heartbeat error {err}")

    def leave(self) -> None:
        body = (_Writer().string(self.group_id)
                .string(self.member_id).done())
        r = self._cli._call(API_LEAVE_GROUP, 0, body)
        r.int16()
        self.assignment = {}

    def commit(self, offsets: Dict[Tuple[str, int], int]) -> None:
        """OffsetCommit v2 under the current generation (fenced: a deposed
        member's commit is rejected by the coordinator)."""
        by_topic: Dict[str, List[Tuple[int, int]]] = {}
        for (topic, part), off in offsets.items():
            by_topic.setdefault(topic, []).append((part, off))
        body = (_Writer().string(self.group_id).int32(self.generation)
                .string(self.member_id).int64(-1)
                .array(sorted(by_topic.items()),
                       lambda w, t: w.string(t[0]).array(
                           sorted(t[1]), lambda w, p: w.int32(p[0])
                           .int64(p[1]).string(None)))
                .done())
        r = self._cli._call(API_OFFSET_COMMIT, 2, body)
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                r.int32()
                err = r.int16()
                if err:
                    raise ValueError(f"OffsetCommit error {err}")

    def committed(self, parts: List[Tuple[str, int]]
                  ) -> Dict[Tuple[str, int], int]:
        """OffsetFetch v1: committed offset per partition (-1 = none)."""
        by_topic: Dict[str, List[int]] = {}
        for topic, part in parts:
            by_topic.setdefault(topic, []).append(part)
        body = (_Writer().string(self.group_id)
                .array(sorted(by_topic.items()),
                       lambda w, t: w.string(t[0]).array(
                           sorted(t[1]), lambda w, p: w.int32(p)))
                .done())
        r = self._cli._call(API_OFFSET_FETCH, 1, body)
        out: Dict[Tuple[str, int], int] = {}
        for _ in range(r.int32()):
            topic = r.string()
            for _ in range(r.int32()):
                part = r.int32()
                off = r.int64()
                r.string()                           # metadata
                err = r.int16()
                if err:
                    raise ValueError(f"OffsetFetch error {err}")
                out[(topic, part)] = off
        return out

    # -- data plane (v2 batches) -------------------------------------------
    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20
              ) -> Tuple[List[DecodedRecord], int]:
        return fetch_v2(self._cli, topic, partition, offset, max_bytes)

    def close(self) -> None:
        self._cli.close()


class _Rejoin(Exception):
    """Internal: the coordinator demands a fresh join."""


# ---------------------------------------------------------------------------
# v2 data-plane calls (usable from the plain wire client too)
# ---------------------------------------------------------------------------

def produce_v2(cli: KafkaWireClient, topic: str, partition: int,
               records: List[Record]) -> int:
    """Produce v3 (message format v2); returns the assigned base offset."""
    batch = encode_record_batch(0, records)
    body = (_Writer().string(None)                   # transactional_id
            .int16(-1).int32(10_000)
            .array([(topic, [(partition, batch)])],
                   lambda w, t: w.string(t[0]).array(
                       t[1], lambda w, p: w.int32(p[0]).bytes_(p[1])))
            .done())
    r = cli._call(0, 3, body)                        # Produce v3
    for _ in range(r.int32()):
        r.string()
        for _ in range(r.int32()):
            r.int32()
            err = r.int16()
            base = r.int64()
            r.int64()                                # log_append_time
            if err:
                raise ValueError(f"produce(v3) error {err}")
            r.int32()                                # throttle_time
            return base
    raise ValueError("empty produce response")


def fetch_v2(cli: KafkaWireClient, topic: str, partition: int, offset: int,
             max_bytes: int = 1 << 20
             ) -> Tuple[List[DecodedRecord], int]:
    """Fetch v4 (record-batch responses) -> (records, high watermark)."""
    body = (_Writer().int32(-1).int32(100).int32(1)
            .int32(max_bytes).int8(0)                # max_bytes, isolation
            .array([(topic, [(partition, offset, max_bytes)])],
                   lambda w, t: w.string(t[0]).array(
                       t[1], lambda w, p: w.int32(p[0]).int64(p[1])
                       .int32(p[2])))
            .done())
    r = cli._call(1, 4, body)                        # Fetch v4
    r.int32()                                        # throttle_time
    for _ in range(r.int32()):
        r.string()
        for _ in range(r.int32()):
            r.int32()
            err = r.int16()
            hw = r.int64()
            r.int64()                                # last_stable_offset
            r.array(lambda r: (r.int64(), r.int64()))  # aborted txns
            data = r.bytes_() or b""
            if err == 1:
                raise IndexError(f"offset {offset} out of range (hw {hw})")
            if err:
                raise ValueError(f"fetch(v4) error {err}")
            return decode_record_batches(data), hw
    raise ValueError("empty fetch response")


class IncrementalFetcher:
    """KIP-227 incremental fetch session (fetch v7): the FIRST poll is a
    full fetch establishing a broker-side session; every later poll sends
    only partitions whose fetch offset CHANGED since the last request,
    and the broker answers with only partitions carrying news — the
    steady-state idle poll is a near-empty exchange.

    ``poll() -> {partition: [DecodedRecord, ...]}``; offsets advance
    automatically as records are returned.  Per-partition errors do NOT
    raise (the healthy partitions' records would be lost): the errored
    partition lands in ``partition_errors``, leaves the local offset map,
    and is forgotten from the broker session on the next request —
    callers inspect ``partition_errors`` and re-add with a corrected
    offset."""

    def __init__(self, cli: KafkaWireClient, topic: str,
                 partitions: List[int], start_offsets=None,
                 max_bytes: int = 1 << 20):
        self.cli = cli
        self.topic = topic
        self.max_bytes = max_bytes
        self.offsets: Dict[int, int] = {
            p: (start_offsets or {}).get(p, 0) for p in partitions}
        self.session_id = 0
        self.epoch = 0
        self._sent: Dict[int, int] = {}       # offsets as of last request
        self._forget: List[int] = []          # drop from the session
        self.partition_errors: Dict[int, int] = {}

    def _request(self, parts: List[int], forget: List[int]) -> '_Reader':
        from flink_tpu.connectors.kafka import _API_FETCH
        body = (_Writer().int32(-1).int32(100).int32(1)
                .int32(self.max_bytes).int8(0)
                .int32(self.session_id).int32(self.epoch)
                .array([(self.topic,
                         [(p, self.offsets[p]) for p in parts])],
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, pp: w.int32(pp[0])
                           .int64(pp[1]).int64(0).int32(self.max_bytes)))
                .array([(self.topic, list(forget))] if forget else [],
                       lambda w, t: w.string(t[0]).array(
                           t[1], lambda w, p: w.int32(p))))
        return self.cli._call(_API_FETCH, 7, body.done())

    def poll(self) -> Dict[int, List[DecodedRecord]]:
        from flink_tpu.connectors.kafka import (
            _ERR_FETCH_SESSION_ID_NOT_FOUND,
            _ERR_INVALID_FETCH_SESSION_EPOCH)
        self.partition_errors = {}
        if self.epoch == 0:
            parts = sorted(self.offsets)         # full fetch
        else:
            parts = sorted(p for p, o in self.offsets.items()
                           if self._sent.get(p) != o)
        forget, self._forget = self._forget, []
        r = self._request(parts, forget)
        r.int32()                                # throttle
        err = r.int16()
        sid = r.int32()
        if err in (_ERR_FETCH_SESSION_ID_NOT_FOUND,
                   _ERR_INVALID_FETCH_SESSION_EPOCH):
            self.session_id, self.epoch = 0, 0   # re-establish full
            self._sent = {}
            return self.poll()
        if err:
            raise ValueError(f"fetch(v7) error {err}")
        if self.epoch == 0 and sid:
            self.session_id = sid
        self.epoch += 1
        for p in parts:
            self._sent[p] = self.offsets[p]
        out: Dict[int, List[DecodedRecord]] = {}
        for _ in range(r.int32()):
            r.string()
            for _ in range(r.int32()):
                part = r.int32()
                perr = r.int16()
                r.int64()                        # high watermark
                r.int64()                        # last_stable_offset
                r.int64()                        # log_start_offset
                r.array(lambda r: (r.int64(), r.int64()))  # aborted
                data = r.bytes_() or b""
                if perr:
                    # healthy partitions keep flowing; the bad one exits
                    # the session until the caller re-adds it
                    self.partition_errors[part] = perr
                    self.offsets.pop(part, None)
                    self._sent.pop(part, None)
                    self._forget.append(part)
                    continue
                recs = decode_record_batches(data)
                if recs:
                    out[part] = recs
                    self.offsets[part] = recs[-1][0] + 1
        return out

    def add_partition(self, partition: int, offset: int) -> None:
        """(Re-)track a partition (e.g. after a partition_errors entry)."""
        self.offsets[partition] = offset


# ---------------------------------------------------------------------------
# group source (committed-offset restart)
# ---------------------------------------------------------------------------

class KafkaGroupSource:
    """Source with committed-offset restart — the reference KafkaSource's
    exact model (``KafkaSource.java:1``): partitions are assigned
    MANUALLY (split ``i`` owns partitions ``p % parallelism == i``, the
    enumerator's round-robin), while ``group_id`` is used only for
    OffsetFetch/OffsetCommit — the ``OffsetsInitializer.committedOffsets``
    behaviour.  The reference deliberately avoids group-membership
    assignment for its readers (a mid-read rebalance would yank partitions
    from a running split); :class:`KafkaGroupConsumer` provides the full
    membership dance for clients that want it.

    Each split reads its partitions from the committed offset (earliest
    when none) to the high watermark at start, committing as it goes, so a
    restarted job resumes where the last run's commits left off."""

    bounded = True

    def __init__(self, host: str, port: int, topic: str, group_id: str,
                 timestamp_column: Optional[str] = None,
                 batch_rows: int = 1024, commit_every_rows: int = 4096):
        self.host, self.port = host, port
        self.topic = topic
        self.group_id = group_id
        self.timestamp_column = timestamp_column
        self.batch_rows = batch_rows
        self.commit_every_rows = commit_every_rows

    def create_splits(self, parallelism: int):
        from flink_tpu.connectors.sources import SourceSplit

        n = max(1, parallelism)

        class _Split(SourceSplit):
            def split_id(_self) -> str:
                return f"{self.topic}@{self.group_id}-{_self.index}"

            def read(_self):
                return self._read_split(_self.index, _self.of)

        return [_Split(self, i, n) for i in range(n)]

    def _read_split(self, index: int, of: int) -> Iterator[Any]:
        import json

        from flink_tpu.core.batch import RecordBatch

        c = KafkaGroupConsumer(self.host, self.port, self.group_id,
                               [self.topic], client_id=f"split-{index}")
        try:
            n_parts = c._partition_counts().get(self.topic, 0)
            parts = [p for p in range(n_parts) if p % of == index]
            if not parts:
                return
            committed = c.committed([(self.topic, p) for p in parts])
            positions = {p: max(committed.get((self.topic, p), -1) + 1, 0)
                         for p in parts}
            ends = {p: c._cli.latest_offset(self.topic, p) for p in parts}
            rows: List[dict] = []
            since_commit = 0
            for p in parts:
                while positions[p] < ends[p]:
                    recs, _hw = c.fetch(self.topic, p, positions[p])
                    if not recs:
                        break
                    for off, _ts, _k, v, _h in recs:
                        if off >= ends[p]:
                            break
                        positions[p] = off + 1
                        since_commit += 1
                        if v is not None:
                            rows.append(json.loads(v.decode()))
                    while len(rows) >= self.batch_rows:
                        chunk = rows[:self.batch_rows]
                        rows = rows[self.batch_rows:]
                        yield self._batch(chunk, RecordBatch)
                    if since_commit >= self.commit_every_rows:
                        c.commit({(self.topic, q): positions[q] - 1
                                  for q in parts if positions[q] > 0})
                        since_commit = 0
            if rows:
                yield self._batch(rows, RecordBatch)
            # final commit: the next run resumes after everything read
            # (generation -1 + empty member = the simple-client commit path)
            c.commit({(self.topic, q): positions[q] - 1
                      for q in parts if positions[q] > 0})
        finally:
            c.close()

    def _batch(self, rows, RecordBatch):
        cols = {k: np.asarray([r[k] for r in rows]) for k in rows[0]}
        if self.timestamp_column is not None:
            ts = np.asarray(cols[self.timestamp_column], np.int64)
            return RecordBatch(cols, timestamps=ts)
        return RecordBatch(cols)
