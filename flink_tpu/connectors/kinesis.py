"""Kinesis connector: JSON-over-HTTP wire service, client, source, sink.

Analog of ``flink-connectors/flink-connector-kinesis``
(``FlinkKinesisConsumer`` + ``FlinkKinesisProducer``): the source reads
shards with per-shard SEQUENCE-NUMBER checkpointing (the consumer's
``sequenceNumsToRestore``) via the positioned-reader seam, the sink
batches ``PutRecords`` calls (at-least-once).

The wire dialect is the real Kinesis Data Streams API shape: POST ``/``
with ``X-Amz-Target: Kinesis_20131202.<Action>`` and a JSON body,
records base64-encoded, opaque shard iterators, ``TRIM_HORIZON`` /
``AT_SEQUENCE_NUMBER`` / ``AFTER_SEQUENCE_NUMBER`` / ``LATEST`` iterator
types, and SigV4 request signing (``service="kinesis"``) reusing the S3
module's signer — ``KinesisService`` checks that the signed
Authorization header carries the configured access-key ID (a presence
check, NOT a full signature re-derivation; that lives in the S3
server).  Partition keys route to shards by hash (real Kinesis splits
the md5 hash-key RANGE across shards; same distribution, simpler
bookkeeping).
"""

from __future__ import annotations

import base64
import hashlib
import json
import threading
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.connectors.sources import Source, SourceSplit
from flink_tpu.connectors.util import json_default

_TARGET_PREFIX = "Kinesis_20131202."


class KinesisError(Exception):
    def __init__(self, error_type: str, message: str = ""):
        self.error_type = error_type
        super().__init__(f"{error_type}: {message}")


def _shard_of(partition_key: str, n_shards: int) -> int:
    h = int(hashlib.md5(partition_key.encode()).hexdigest(), 16)
    return h % n_shards


class KinesisService:
    """Single-node Kinesis Data Streams service: streams of shards, each
    an append-only record list (sequence number = list index)."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0,
                 access_key: Optional[str] = None,
                 secret_key: Optional[str] = None):
        self._lock = threading.Lock()
        #: stream -> [shard] where shard = [(partition_key, data bytes)]
        self.streams: Dict[str, List[List[Tuple[str, bytes]]]] = {}
        self._access, self._secret = access_key, secret_key
        svc = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def _reply(self, code: int, body: dict) -> None:
                data = json.dumps(body).encode()
                self.send_response(code)
                self.send_header("Content-Type",
                                 "application/x-amz-json-1.1")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_POST(self):  # noqa: N802
                n = int(self.headers.get("Content-Length") or 0)
                body = self.rfile.read(n) if n else b"{}"
                if svc._access is not None and not self._authorized():
                    return self._reply(403, {
                        "__type": "AccessDeniedException"})
                target = self.headers.get("X-Amz-Target", "")
                if not target.startswith(_TARGET_PREFIX):
                    return self._reply(400, {
                        "__type": "UnknownOperationException"})
                action = target[len(_TARGET_PREFIX):]
                try:
                    req = json.loads(body or b"{}")
                    out = svc._dispatch(action, req)
                except KinesisError as e:
                    return self._reply(400, {"__type": e.error_type,
                                             "message": str(e)})
                except (KeyError, ValueError, TypeError) as e:
                    return self._reply(400, {
                        "__type": "ValidationException",
                        "message": str(e)})
                self._reply(200, out)

            def _authorized(self) -> bool:
                # presence-of-credential check: the full SigV4 re-derivation
                # lives in the S3 server; here the signed request must at
                # least carry a matching access key id
                auth = self.headers.get("Authorization", "")
                return f"Credential={svc._access}/" in auth

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        daemon=True)
        self._thread.start()

    # -- actions ------------------------------------------------------------
    def _dispatch(self, action: str, req: dict) -> dict:
        fn = getattr(self, f"_a_{action}", None)
        if fn is None:
            raise KinesisError("UnknownOperationException", action)
        return fn(req)

    def _stream(self, name: str) -> List[List[Tuple[str, bytes]]]:
        s = self.streams.get(name)
        if s is None:
            raise KinesisError("ResourceNotFoundException", name)
        return s

    def _shard(self, name: str, idx: int) -> List[Tuple[str, bytes]]:
        shards = self._stream(name)
        if not 0 <= idx < len(shards):
            raise KinesisError("ResourceNotFoundException",
                               f"{name} shard {idx}")
        return shards[idx]

    def _a_CreateStream(self, req: dict) -> dict:  # noqa: N802
        name = req["StreamName"]
        n = int(req.get("ShardCount", 1))
        with self._lock:
            if name in self.streams:
                raise KinesisError("ResourceInUseException", name)
            self.streams[name] = [[] for _ in range(n)]
        return {}

    def _a_DescribeStream(self, req: dict) -> dict:  # noqa: N802
        with self._lock:
            shards = self._stream(req["StreamName"])
            return {"StreamDescription": {
                "StreamName": req["StreamName"],
                "StreamStatus": "ACTIVE",
                "Shards": [{"ShardId": f"shardId-{i:012d}"}
                           for i in range(len(shards))]}}

    def _a_ListShards(self, req: dict) -> dict:  # noqa: N802
        with self._lock:
            shards = self._stream(req["StreamName"])
            return {"Shards": [{"ShardId": f"shardId-{i:012d}"}
                               for i in range(len(shards))]}

    def _a_PutRecord(self, req: dict) -> dict:  # noqa: N802
        data = base64.b64decode(req["Data"])
        pk = req["PartitionKey"]
        with self._lock:
            shards = self._stream(req["StreamName"])
            i = _shard_of(pk, len(shards))
            shards[i].append((pk, data))
            seq = len(shards[i]) - 1
        return {"ShardId": f"shardId-{i:012d}",
                "SequenceNumber": str(seq)}

    def _a_PutRecords(self, req: dict) -> dict:  # noqa: N802
        out = []
        failed = 0
        with self._lock:
            shards = self._stream(req["StreamName"])
            for rec in req["Records"]:
                data = base64.b64decode(rec["Data"])
                pk = rec["PartitionKey"]
                i = _shard_of(pk, len(shards))
                shards[i].append((pk, data))
                out.append({"ShardId": f"shardId-{i:012d}",
                            "SequenceNumber": str(len(shards[i]) - 1)})
        return {"FailedRecordCount": failed, "Records": out}

    @staticmethod
    def _shard_index(shard_id: str) -> int:
        return int(shard_id.rsplit("-", 1)[-1])

    def _a_GetShardIterator(self, req: dict) -> dict:  # noqa: N802
        name = req["StreamName"]
        idx = self._shard_index(req["ShardId"])
        typ = req["ShardIteratorType"]
        with self._lock:
            shard = self._shard(name, idx)
            if typ == "TRIM_HORIZON":
                pos = 0
            elif typ == "LATEST":
                pos = len(shard)
            elif typ == "AT_SEQUENCE_NUMBER":
                pos = int(req["StartingSequenceNumber"])
            elif typ == "AFTER_SEQUENCE_NUMBER":
                pos = int(req["StartingSequenceNumber"]) + 1
            else:
                raise KinesisError("ValidationException", typ)
        return {"ShardIterator": f"{name}|{idx}|{pos}"}

    def _a_GetRecords(self, req: dict) -> dict:  # noqa: N802
        name, idx_s, pos_s = req["ShardIterator"].split("|")
        idx, pos = int(idx_s), int(pos_s)
        limit = int(req.get("Limit", 10_000))
        with self._lock:
            shard = self._shard(name, idx)
            chunk = shard[pos:pos + limit]
            end = pos + len(chunk)
            behind = len(shard) - end
        return {
            "Records": [{
                "SequenceNumber": str(pos + j),
                "PartitionKey": pk,
                "Data": base64.b64encode(data).decode(),
            } for j, (pk, data) in enumerate(chunk)],
            "NextShardIterator": f"{name}|{idx}|{end}",
            "MillisBehindLatest": 0 if behind == 0 else 1,
        }

    def close(self) -> None:
        self._httpd.shutdown()


class KinesisClient:
    """SigV4-signed JSON client (the AWS SDK analog the connector uses)."""

    def __init__(self, endpoint: str, access_key: str = "test",
                 secret_key: str = "test", region: str = "us-east-1",
                 timeout_s: float = 10.0):
        self.endpoint = endpoint.rstrip("/")
        self.access_key, self.secret_key = access_key, secret_key
        self.region = region
        self.timeout_s = timeout_s

    def call(self, action: str, body: dict) -> dict:
        from flink_tpu.filesystems.s3 import sign_v4
        payload = json.dumps(body).encode()
        host = self.endpoint.split("://", 1)[-1]
        headers = {
            "host": host,
            "X-Amz-Target": _TARGET_PREFIX + action,
            "Content-Type": "application/x-amz-json-1.1",
        }
        headers = sign_v4("POST", self.endpoint + "/", headers,
                          hashlib.sha256(payload).hexdigest(),
                          self.access_key, self.secret_key, self.region,
                          service="kinesis")
        req = urllib.request.Request(self.endpoint + "/", data=payload,
                                     method="POST", headers=headers)
        try:
            with urllib.request.urlopen(req,
                                        timeout=self.timeout_s) as resp:
                return json.loads(resp.read() or b"{}")
        except urllib.error.HTTPError as e:
            try:
                err = json.loads(e.read() or b"{}")
            except ValueError:
                err = {}
            raise KinesisError(err.get("__type", f"HTTP{e.code}"),
                               err.get("message", "")) from e
        except urllib.error.URLError as e:
            raise KinesisError("ConnectionError", str(e.reason)) from e

    # convenience wrappers
    def create_stream(self, name: str, shards: int = 1) -> None:
        self.call("CreateStream", {"StreamName": name,
                                   "ShardCount": shards})

    def list_shards(self, name: str) -> List[str]:
        return [s["ShardId"] for s in
                self.call("ListShards", {"StreamName": name})["Shards"]]

    def put_records(self, name: str,
                    records: List[Tuple[str, bytes]]) -> None:
        self.call("PutRecords", {"StreamName": name, "Records": [
            {"PartitionKey": pk,
             "Data": base64.b64encode(data).decode()}
            for pk, data in records]})

    def shard_iterator(self, name: str, shard_id: str,
                       after_sequence: Optional[int] = None) -> str:
        req = {"StreamName": name, "ShardId": shard_id}
        if after_sequence is None:
            req["ShardIteratorType"] = "TRIM_HORIZON"
        else:
            req["ShardIteratorType"] = "AFTER_SEQUENCE_NUMBER"
            req["StartingSequenceNumber"] = str(after_sequence)
        return self.call("GetShardIterator", req)["ShardIterator"]

    def get_records(self, iterator: str, limit: int = 10_000) -> dict:
        return self.call("GetRecords", {"ShardIterator": iterator,
                                        "Limit": limit})


# ---------------------------------------------------------------------------
# source / sink
# ---------------------------------------------------------------------------


class _PositionedShardReader:
    """Iterator over one shard's batches; ``position`` = records already
    emitted (the per-shard sequence-number checkpoint of
    ``FlinkKinesisConsumer``)."""

    def __init__(self, source: "KinesisSource", shard_id: str,
                 start: int):
        self.position = int(start)
        self._it = source._read_shard(shard_id, self.position)

    def __iter__(self):
        return self

    def __next__(self):
        el = next(self._it)
        self.position += len(el)    # rows already HANDED OVER
        return el


class KinesisShardSplit(SourceSplit):
    def __init__(self, source: "KinesisSource", index: int, total: int,
                 shard_id: str):
        super().__init__(source, index, total)
        self.shard_id = shard_id

    def split_id(self) -> str:
        return f"{self.source.stream}-{self.shard_id}"

    def read(self):
        return self.source.open_split(self, None)


class KinesisSource(Source):
    """Bounded shard scan up to each shard's tip at open: one split per
    shard, JSON row values, resumable positions."""

    def __init__(self, endpoint: str, stream: str,
                 access_key: str = "test", secret_key: str = "test",
                 batch_rows: int = 1024,
                 timestamp_column: Optional[str] = None):
        self.endpoint = endpoint
        self.stream = stream
        self.access_key, self.secret_key = access_key, secret_key
        self.batch_rows = batch_rows
        self.timestamp_column = timestamp_column

    def _client(self) -> KinesisClient:
        return KinesisClient(self.endpoint, self.access_key,
                             self.secret_key)

    def create_splits(self, parallelism: int) -> List[KinesisShardSplit]:
        shard_ids = self._client().list_shards(self.stream)
        return [KinesisShardSplit(self, i, len(shard_ids), sid)
                for i, sid in enumerate(shard_ids)]

    def open_split(self, split: KinesisShardSplit,
                   position: Optional[int]) -> _PositionedShardReader:
        return _PositionedShardReader(self, split.shard_id, position or 0)

    def _read_shard(self, shard_id: str, start: int):
        from flink_tpu.core.batch import RecordBatch

        c = self._client()
        it = c.shard_iterator(self.stream, shard_id,
                              after_sequence=start - 1 if start else None)
        rows: List[dict] = []
        while True:
            res = c.get_records(it, limit=self.batch_rows)
            it = res["NextShardIterator"]
            for rec in res["Records"]:
                rows.append(json.loads(
                    base64.b64decode(rec["Data"]).decode()))
                if len(rows) >= self.batch_rows:
                    yield self._batch(rows, RecordBatch)
                    rows = []
            if res["MillisBehindLatest"] == 0:
                break               # caught up to the tip at open: bounded
        if rows:
            yield self._batch(rows, RecordBatch)

    def _batch(self, rows, _RecordBatch):
        from flink_tpu.connectors.util import rows_to_batch
        return rows_to_batch(rows, self.timestamp_column)


class KinesisSink:
    """``FlinkKinesisProducer`` analog: rows publish as JSON via batched
    PutRecords (at-least-once; flushed on checkpoint and close)."""

    clone_per_subtask = True

    def __init__(self, endpoint: str, stream: str,
                 partition_key_column: Optional[str] = None,
                 access_key: str = "test", secret_key: str = "test",
                 buffer_rows: int = 500):
        self.endpoint = endpoint
        self.stream = stream
        self.partition_key_column = partition_key_column
        self.access_key, self.secret_key = access_key, secret_key
        self.buffer_rows = buffer_rows
        self._client: Optional[KinesisClient] = None
        self._buf: List[Tuple[str, bytes]] = []
        self._n = 0

    def _cli(self) -> KinesisClient:
        if self._client is None:
            self._client = KinesisClient(self.endpoint, self.access_key,
                                         self.secret_key)
        return self._client

    def open(self, ctx) -> None:
        self._cli()

    def write_batch(self, batch) -> None:
        for r in batch.to_rows():
            pk = (str(r[self.partition_key_column])
                  if self.partition_key_column is not None
                  else str(self._n))
            self._n += 1
            self._buf.append((pk, json.dumps(
                r, default=json_default).encode()))
        if len(self._buf) >= self.buffer_rows:
            self._flush()

    def _flush(self) -> None:
        if self._buf:
            self._cli().put_records(self.stream, self._buf)
            self._buf = []

    def snapshot_state(self) -> Dict[str, Any]:
        self._flush()               # flush-on-checkpoint: at-least-once
        return {}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._buf = []

    def end_input(self) -> None:
        self._flush()

    def close(self) -> None:
        try:
            self._flush()
        except KinesisError:
            pass
        self._client = None
