"""Runtime split enumeration: the FLIP-27 ``SplitEnumerator`` on the
coordinator (VERDICT r1 #6).

The reference runs a ``SplitEnumerator`` inside the JobMaster's
``SourceCoordinator`` (``flink-runtime/.../source/coordinator/
SourceCoordinator.java:75``): readers send ``RequestSplitEvent``s over RPC
(handled at ``:155-170``), the enumerator assigns splits one at a time, and
its state is snapshotted into every checkpoint (``checkpointCoordinator``
path ``:229``).  This module is the framework-side contract plus a
directory-watching file source whose split list GROWS while the job runs —
the dynamic case static deploy-time split creation cannot express.

Runtime wiring: ``cluster/minicluster.py`` hosts a ``SourceCoordinator``
(same process, RPC collapsed to a locked call) and ``cluster/distributed.py``
carries ``split_request``/``split_assign`` control messages between worker
processes and the coordinator (the actual RPC case)."""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

from flink_tpu.connectors.sources import Source, SourceSplit


class SplitEnumerator:
    """Coordinator-side split assignment (``SplitEnumerator.java`` analog).

    Contract: ``next_split`` hands out each split exactly once; ``None``
    with ``done() == False`` means "nothing right now, poll again" (an
    unbounded directory may grow); ``done() == True`` ends the reader."""

    def next_split(self, reader_id: int) -> Optional[SourceSplit]:
        raise NotImplementedError

    def done(self) -> bool:
        raise NotImplementedError

    def snapshot_state(self) -> Dict[str, Any]:
        """Checkpointed with the job (``SourceCoordinator.java:229``)."""
        return {}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        pass

    def reclaim(self, split) -> None:
        """Restore reconciliation: a split found in a READER's restored
        snapshot is owned by that reader even if it was assigned after this
        enumerator's snapshot — never hand it out again.  Accepts a split
        object OR its plain ``split_id()`` string (readers snapshot ids)."""
        pass


class _StaticEnumerator(SplitEnumerator):
    """Wraps a fixed split list (the deploy-time behavior, made requestable).

    Tracks the assigned-id SET (not a cursor) and honors ``reclaim()``: a
    split handed out after this enumerator's trigger-time snapshot but owned
    by a reader at the barrier is re-marked assigned on restore instead of
    being assigned twice (duplicate reads)."""

    def __init__(self, splits: List[SourceSplit]):
        from flink_tpu.connectors.sources import split_id_of

        self._splits = list(splits)
        self._ids = [split_id_of(s) for s in self._splits]  # precomputed
        self._assigned: set = set()
        self._cursor = 0     # first possibly-unassigned position
        self._lock = threading.Lock()

    def next_split(self, reader_id: int) -> Optional[SourceSplit]:
        with self._lock:
            while self._cursor < len(self._splits):
                i = self._cursor
                self._cursor += 1
                if self._ids[i] not in self._assigned:
                    self._assigned.add(self._ids[i])
                    return self._splits[i]
            return None

    def done(self) -> bool:
        with self._lock:
            return (self._cursor >= len(self._splits)
                    or all(i in self._assigned
                           for i in self._ids[self._cursor:]))

    def snapshot_state(self) -> Dict[str, Any]:
        with self._lock:
            return {"assigned": sorted(self._assigned)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        with self._lock:
            if "next" in snap:   # pre-r3 cursor snapshots
                self._assigned = set(self._ids[:snap["next"]])
            else:
                self._assigned = set(snap.get("assigned", []))
            self._cursor = 0

    def reclaim(self, split) -> None:
        from flink_tpu.connectors.sources import split_id_of

        if split is not None:
            with self._lock:
                self._assigned.add(
                    split if isinstance(split, str) else split_id_of(split))


class DynamicFileSource(Source):
    """Directory-watching file source: every file is one split, NEW files
    appearing while the job runs become new splits (the continuous
    ``FileSource`` / ``ContinuousFileSplitEnumerator`` behavior).

    ``done_marker``: enumeration finishes once a file by this name exists
    AND every other file has been assigned — giving bounded tests a clean
    end; without the marker the source is unbounded."""

    def __init__(self, directory: str, format: str = "csv",
                 done_marker: Optional[str] = "_DONE",
                 timestamp_column: Optional[str] = None):
        self.directory = directory
        self.format = format
        self.done_marker = done_marker
        self.timestamp_column = timestamp_column
        self.bounded = done_marker is not None

    # static fallback (executors without runtime coordination read the
    # directory as it looks at deploy time)
    def create_splits(self, parallelism: int) -> List[SourceSplit]:
        enum = DirectoryEnumerator(self)
        out: List[SourceSplit] = []
        while True:
            s = enum.next_split(0)
            if s is None:
                break
            out.append(s)
        return out

    def create_enumerator(self) -> "DirectoryEnumerator":
        return DirectoryEnumerator(self)

    def read_file(self, path: str, start_row: int = 0):
        from flink_tpu.connectors.file_source import FileSource

        fs = FileSource(path, format=self.format,
                        timestamp_column=self.timestamp_column)
        return fs._read_file(path, start_row)


class FilePathSplit(SourceSplit):
    """One file as a split, resumable at a row offset."""

    def __init__(self, source: DynamicFileSource, path: str):
        super().__init__(source, 0, 1)
        self.path = path

    def split_id(self) -> str:
        return self.path

    def read(self):
        return self.source.read_file(self.path, 0)


class DirectoryEnumerator(SplitEnumerator):
    """Scans the directory on every request; assigns unseen files in sorted
    order.  Snapshot = the assigned-file set (so restore never re-reads a
    file a reader already owns — in-flight progress lives in the READER's
    snapshot, exactly the reference split ownership model)."""

    def __init__(self, source: DynamicFileSource):
        self.source = source
        self._assigned: set = set()
        self._lock = threading.Lock()

    def _scan(self) -> List[str]:
        d = self.source.directory
        try:
            names = sorted(os.listdir(d))
        except FileNotFoundError:
            return []
        return [os.path.join(d, n) for n in names
                if not n.startswith("_") and not n.startswith(".")]

    def next_split(self, reader_id: int) -> Optional[FilePathSplit]:
        with self._lock:
            for path in self._scan():
                if path not in self._assigned:
                    self._assigned.add(path)
                    return FilePathSplit(self.source, path)
            return None

    def done(self) -> bool:
        marker = self.source.done_marker
        if marker is None:
            return False
        if not os.path.exists(os.path.join(self.source.directory, marker)):
            return False
        with self._lock:
            return all(p in self._assigned for p in self._scan())

    def snapshot_state(self) -> Dict[str, Any]:
        with self._lock:
            return {"assigned": sorted(self._assigned)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        with self._lock:
            self._assigned = set(snap.get("assigned", []))

    def reclaim(self, split) -> None:
        # FilePathSplit.split_id() IS the path, so ids land in the same set
        if split is not None:
            with self._lock:
                self._assigned.add(
                    split if isinstance(split, str) else split.path)


class SourceCoordinator:
    """Per-job registry of live enumerators (the ``SourceCoordinator``
    collapsed onto the in-process JobMaster; the multi-process path sends
    the same requests as control-plane messages)."""

    def __init__(self):
        self._enums: Dict[str, SplitEnumerator] = {}

    def register(self, vertex_uid: str, enum: SplitEnumerator) -> None:
        self._enums[vertex_uid] = enum

    def request_split(self, vertex_uid: str, reader_id: int):
        """-> (split | None, done: bool)"""
        enum = self._enums[vertex_uid]
        s = enum.next_split(reader_id)
        return s, (s is None and enum.done())

    def snapshot(self) -> Dict[str, Any]:
        return {uid: e.snapshot_state() for uid, e in self._enums.items()}

    def restore(self, snap: Optional[Dict[str, Any]]) -> None:
        for uid, s in (snap or {}).items():
            if uid in self._enums:
                self._enums[uid].restore_state(s)
