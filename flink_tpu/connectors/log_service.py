"""Log service: a standalone broker any process can talk to over HTTP.

The external-system connector (VERDICT r1 #8): the reference's flagship
connector is Kafka (``flink-connectors/flink-connector-kafka``:
``KafkaSource`` FLIP-27 + transactional ``KafkaSink``); no broker ships in
this environment, so this module provides the same shape as a real network
service — a **broker process** (``python -m flink_tpu logservice``) serving
topics/partitions/offsets over HTTP, durable on disk via
:class:`~flink_tpu.connectors.partitioned_log.PartitionedLog`, plus client
``Source``/``Sink`` classes that speak the wire protocol from ANY process.

Wire protocol (HTTP, bodies are CRC-framed FTB record batches):
  - ``POST /topics/{t}?partitions=N``                create topic
  - ``GET  /topics/{t}``                             -> meta JSON
  - ``POST /topics/{t}/{p}/append``                  append one batch;
        idempotent-producer headers ``X-Producer-Id``/``X-Seq`` dedupe
        retried appends (the Kafka idempotent-producer sequence protocol)
  - ``GET  /topics/{t}/{p}/fetch?offset=B&max_bytes=M``
        -> framed batches, ``X-Next-Offset`` header

Exactly-once sink: batches stage in the checkpoint (2PC,
``TwoPhaseCommitSinkFunction`` analog) and commit with producer sequences,
so a replayed commit after restore is deduplicated broker-side.
"""

from __future__ import annotations

import io
import json
import os
import threading
import urllib.parse
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from flink_tpu.connectors.sources import Source, SourceSplit
from flink_tpu.core.batch import RecordBatch, StreamElement, Watermark


# --------------------------------------------------------------------------
# broker
# --------------------------------------------------------------------------

class LogServiceBroker:
    """Durable topic/partition/offset broker over HTTP (threaded)."""

    def __init__(self, directory: str, host: str = "127.0.0.1",
                 port: int = 0):
        from flink_tpu.connectors.partitioned_log import PartitionedLog

        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        self._logs: Dict[str, PartitionedLog] = {}
        #: idempotent producers: (topic, part, producer) -> last seq
        self._seqs: Dict[Tuple[str, int, str], int] = {}
        self._lock = threading.Lock()
        self._seq_path = os.path.join(directory, "_producer_seqs.json")
        if os.path.exists(self._seq_path):
            with open(self._seq_path) as f:
                for k, v in json.load(f).items():
                    topic, part, producer = k.rsplit("|", 2)
                    self._seqs[(topic, int(part), producer)] = v
        for name in os.listdir(directory):
            d = os.path.join(directory, name)
            if os.path.isdir(d) and PartitionedLog.exists(d):
                self._logs[name] = PartitionedLog(d)
        broker = self
        errlog = open(os.devnull, "w")

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, fmt, *args):  # quiet
                errlog.write((fmt % args) + "\n")

            def _json(self, code: int, obj) -> None:
                body = json.dumps(obj).encode()
                self.send_response(code)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                q = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query)
                try:
                    if len(parts) == 2 and parts[0] == "topics":
                        n = int(q.get("partitions", ["1"])[0])
                        broker.create_topic(parts[1], n)
                        return self._json(200, {"ok": True})
                    if len(parts) == 4 and parts[0] == "topics" \
                            and parts[3] == "append":
                        ln = int(self.headers["Content-Length"])
                        payload = self.rfile.read(ln)
                        end = broker.append(
                            parts[1], int(parts[2]), payload,
                            self.headers.get("X-Producer-Id"),
                            self.headers.get("X-Seq"))
                        return self._json(200, {"end_offset": end})
                    self._json(404, {"error": "not found"})
                except Exception as e:  # noqa: BLE001 — wire boundary
                    self._json(500, {"error": str(e)})

            def do_GET(self):
                parts = [p for p in self.path.split("?")[0].split("/") if p]
                q = urllib.parse.parse_qs(
                    urllib.parse.urlsplit(self.path).query)
                try:
                    if len(parts) == 2 and parts[0] == "topics":
                        return self._json(200, broker.meta(parts[1]))
                    if len(parts) == 4 and parts[0] == "topics" \
                            and parts[3] == "fetch":
                        off = int(q.get("offset", ["0"])[0])
                        mx = int(q.get("max_bytes", ["1048576"])[0])
                        data, nxt = broker.fetch(parts[1], int(parts[2]),
                                                 off, mx)
                        self.send_response(200)
                        self.send_header("Content-Length", str(len(data)))
                        self.send_header("X-Next-Offset", str(nxt))
                        self.end_headers()
                        self.wfile.write(data)
                        return
                    self._json(404, {"error": "not found"})
                except KeyError:
                    self._json(404, {"error": "unknown topic"})
                except Exception as e:  # noqa: BLE001
                    self._json(500, {"error": str(e)})

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self.host, self.port = self._httpd.server_address[:2]
        self.url = f"http://{self.host}:{self.port}"
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="log-broker", daemon=True)

    def start(self) -> "LogServiceBroker":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()

    def serve_forever(self) -> None:
        self._httpd.serve_forever()

    # -- broker ops --------------------------------------------------------
    def create_topic(self, topic: str, partitions: int) -> None:
        from flink_tpu.connectors.partitioned_log import PartitionedLog

        with self._lock:
            if topic not in self._logs:
                self._logs[topic] = PartitionedLog(
                    os.path.join(self.directory, topic), partitions)

    def meta(self, topic: str) -> Dict[str, Any]:
        log = self._logs[topic]
        return {"num_partitions": log.num_partitions,
                "end_offsets": [log.end_offset(p)
                                for p in range(log.num_partitions)]}

    def append(self, topic: str, partition: int, framed: bytes,
               producer: Optional[str], seq: Optional[str]) -> int:
        log = self._logs[topic]
        with self._lock:
            if producer is not None and seq is not None:
                key = (topic, partition, producer)
                if self._seqs.get(key, -1) >= int(seq):
                    return log.end_offset(partition)  # duplicate: dropped
            path = log._path(partition)
            with open(path, "ab") as f:
                f.write(framed)
                f.flush()
                os.fsync(f.fileno())
                end = f.tell()
            # sequence is recorded only AFTER the data is durable: a crash
            # between the two at worst re-admits the producer's retry of the
            # same batch (duplicate, the at-least-once floor) — never drops
            # an acknowledged-but-unwritten batch as a "duplicate"
            if producer is not None and seq is not None:
                self._seqs[(topic, partition, producer)] = int(seq)
                self._persist_seqs()
            return end

    def _persist_seqs(self) -> None:
        tmp = self._seq_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({f"{t}|{p}|{pr}": v
                       for (t, p, pr), v in self._seqs.items()}, f)
        os.replace(tmp, self._seq_path)

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int) -> Tuple[bytes, int]:
        log = self._logs[topic]
        path = log._path(partition)
        end = log.end_offset(partition)
        if offset >= end:
            return b"", offset
        take = min(max_bytes, end - offset)
        with open(path, "rb") as f:
            f.seek(offset)
            data = f.read(take)
        # truncate to whole frames (a fetch never splits a record batch)
        from flink_tpu.formats import frame_span
        whole = frame_span(data)
        return data[:whole], offset + whole


# --------------------------------------------------------------------------
# client
# --------------------------------------------------------------------------

class LogServiceClient:
    """Thin wire-protocol client (usable from any process/language that can
    speak HTTP — this is the boundary an external system integrates at)."""

    def __init__(self, url: str, timeout_s: float = 30.0):
        self.url = url.rstrip("/")
        self.timeout_s = timeout_s

    def _req(self, method: str, path: str, body: Optional[bytes] = None,
             headers: Optional[Dict[str, str]] = None):
        req = urllib.request.Request(self.url + path, data=body,
                                     method=method,
                                     headers=headers or {})
        return urllib.request.urlopen(req, timeout=self.timeout_s)

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        self._req("POST", f"/topics/{topic}?partitions={partitions}").read()

    def meta(self, topic: str) -> Dict[str, Any]:
        with self._req("GET", f"/topics/{topic}") as r:
            return json.loads(r.read())

    def append(self, topic: str, partition: int, batch: RecordBatch,
               producer: Optional[str] = None,
               seq: Optional[int] = None) -> int:
        from flink_tpu.formats import write_frame
        from flink_tpu.native.codec import encode_batch

        buf = io.BytesIO()
        write_frame(buf, encode_batch(batch))
        headers = {}
        if producer is not None:
            headers["X-Producer-Id"] = producer
            headers["X-Seq"] = str(seq)
        with self._req("POST", f"/topics/{topic}/{partition}/append",
                       buf.getvalue(), headers) as r:
            return json.loads(r.read())["end_offset"]

    def fetch(self, topic: str, partition: int, offset: int,
              max_bytes: int = 1 << 20) -> Tuple[List[RecordBatch], int]:
        from flink_tpu.formats import iter_frames
        from flink_tpu.native.codec import decode_batch

        with self._req("GET", f"/topics/{topic}/{partition}/fetch"
                       f"?offset={offset}&max_bytes={max_bytes}") as r:
            nxt = int(r.headers["X-Next-Offset"])
            data = r.read()
        return [decode_batch(p) for p in iter_frames(data)], nxt


class LogServiceSource(Source):
    """FLIP-27 source over the broker: one split per partition, positions
    are byte offsets (the ``KafkaSource`` shape).  Bounded mode reads to the
    end offsets observed at split creation."""

    def __init__(self, url: str, topic: str,
                 timestamp_column: Optional[str] = None):
        self.url = url
        self.topic = topic
        self.timestamp_column = timestamp_column
        self.bounded = True

    def create_splits(self, parallelism: int) -> List[SourceSplit]:
        client = LogServiceClient(self.url)
        meta = client.meta(self.topic)
        return [LogServiceSplit(self, p, meta["num_partitions"],
                                end_offset=meta["end_offsets"][p])
                for p in range(meta["num_partitions"])]

    def read_partition(self, partition: int,
                       end_offset: int) -> Iterator[StreamElement]:
        client = LogServiceClient(self.url)
        off = 0
        max_bytes = 1 << 20
        while off < end_offset:
            batches, nxt = client.fetch(self.topic, partition, off,
                                        max_bytes=max_bytes)
            if nxt == off:
                # a single frame larger than the fetch window: grow and
                # retry (a fetch must always make progress, Kafka's
                # max.partition.fetch.bytes oversize-record behavior)
                if max_bytes >= 1 << 30:
                    raise IOError(
                        f"record batch at offset {off} exceeds 1GiB")
                max_bytes *= 2
                continue
            for b in batches:
                if self.timestamp_column is not None:
                    ts = np.asarray(b.column(self.timestamp_column),
                                    np.int64)
                    b = RecordBatch(dict(b.columns), timestamps=ts)
                    yield b
                    yield Watermark(int(ts.max()))
                else:
                    yield b
            off = nxt


class LogServiceSplit(SourceSplit):
    def __init__(self, source: LogServiceSource, index: int, of: int,
                 end_offset: int):
        super().__init__(source, index, of)
        self.end_offset = end_offset

    def split_id(self) -> str:
        return f"{self.source.topic}-{self.index}"

    def read(self) -> Iterator[StreamElement]:
        return self.source.read_partition(self.index, self.end_offset)


class LogServiceSink:
    """Exactly-once transactional sink into the broker: epochs stage in
    the checkpoint (2PC pre-commit); ``notify_checkpoint_complete`` appends
    with idempotent-producer sequences so replayed commits deduplicate
    broker-side (``KafkaSink`` EXACTLY_ONCE analog)."""

    clone_per_subtask = True

    def __init__(self, url: str, topic: str, num_partitions: int = 1,
                 key_column: Optional[str] = None, producer_id: str = ""):
        import uuid

        self.url = url
        self.topic = topic
        self.num_partitions = num_partitions
        self.key_column = key_column
        self.producer_id = producer_id or uuid.uuid4().hex[:12]
        self._client: Optional[LogServiceClient] = None
        self._epoch: List[RecordBatch] = []
        self._staged: Dict[int, List[RecordBatch]] = {}
        self._rr = 0

    def on_cloned(self) -> None:
        import uuid

        self.producer_id = uuid.uuid4().hex[:12]
        self._epoch = []
        self._staged = {}
        self._txn_ckpt = {}

    def _cli(self) -> LogServiceClient:
        if self._client is None:
            self._client = LogServiceClient(self.url)
            self._client.create_topic(self.topic, self.num_partitions)
        return self._client

    def open(self, ctx) -> None:
        self._cli()

    def write_batch(self, batch: RecordBatch) -> None:
        if len(batch):
            self._epoch.append(batch)

    # -- 2PC hooks (same contract as connectors.partitioned_log.LogSink:
    # snapshot PRE-COMMITS the epoch under an internal txn counter, notify
    # commits every staged txn; replayed commits after restore carry the
    # SAME producer sequences and deduplicate broker-side) -----------------
    def snapshot_state(self) -> Dict[str, Any]:
        from flink_tpu.operators.base import current_checkpoint_id

        self._counter = getattr(self, "_counter", 0) + 1
        self._staged[self._counter] = self._epoch
        # txn -> checkpoint id: notify commits ONLY txns staged for
        # checkpoints <= the notified one (TwoPhaseCommitSinkFunction
        # contract) — if checkpoints ever pipeline, an epoch staged for a
        # later, uncompleted checkpoint must not commit early
        self._txn_ckpt = getattr(self, "_txn_ckpt", {})
        self._txn_ckpt[self._counter] = current_checkpoint_id()
        self._epoch = []
        staged = {cid: [{k: np.asarray(v) for k, v in b.columns.items()}
                        for b in bs] for cid, bs in self._staged.items()}
        # _rr rides the snapshot: a replayed commit must route each batch
        # to the SAME partition, or the per-partition seq dedup misses
        return {"staged": staged, "counter": self._counter,
                "producer_id": self.producer_id, "rr": self._rr,
                "txn_ckpt": dict(self._txn_ckpt)}

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        txn_ckpt = getattr(self, "_txn_ckpt", {})
        for cid in sorted(self._staged):
            staged_for = txn_ckpt.get(cid)
            # None = the runtime gave no id at snapshot time: the legacy
            # notify-before-next-barrier ordering applies — commit
            if staged_for is not None and staged_for > checkpoint_id:
                continue
            self._commit(cid)

    def _commit(self, cid: int) -> None:
        getattr(self, "_txn_ckpt", {}).pop(cid, None)
        for j, batch in enumerate(self._staged.pop(cid, [])):
            # seq = (txn << 20 | j): strictly increasing per producer and
            # identical on replay -> broker-side idempotent dedup
            for part, sub in self._route(batch):
                self._cli().append(self.topic, part, sub,
                                   producer=self.producer_id,
                                   seq=(cid << 20) | j)

    def _route(self, batch: RecordBatch):
        """(partition, sub-batch) routing: stable key hash keeps per-key
        ordering within a partition (LogSink._append semantics)."""
        n_p = self.num_partitions
        if self.key_column is None or n_p == 1:
            self._rr += 1
            return [(self._rr % n_p, batch)]
        from flink_tpu.core.keygroups import hash_keys
        keys = np.asarray(batch.column(self.key_column))
        parts = (np.abs(hash_keys(keys).astype(np.int64)) % n_p)
        return [(int(p), batch.select(parts == p))
                for p in np.unique(parts).tolist()]

    def restore_state(self, snap: Dict[str, Any]) -> None:
        # adopt the snapshot's producer identity: replayed commits must
        # carry the same sequences to deduplicate
        self.producer_id = snap.get("producer_id", self.producer_id)
        self._counter = int(snap.get("counter", 0))
        self._rr = int(snap.get("rr", 0))
        self._epoch = []
        self._txn_ckpt = {int(cid): v
                          for cid, v in snap.get("txn_ckpt", {}).items()}
        self._staged = {int(cid): [RecordBatch(c) for c in bs]
                        for cid, bs in snap.get("staged", {}).items()}
        # txns staged in a completed checkpoint are owed to the broker
        for cid in sorted(self._staged):
            self._commit(cid)

    def flush(self) -> None:
        """Bounded end-of-input: staged (older) txns land before the final
        epoch's rows (consumer last-value-per-key ordering)."""
        for cid in sorted(self._staged):
            self._commit(cid)
        for j, batch in enumerate(self._epoch):
            for part, sub in self._route(batch):
                self._cli().append(self.topic, part, sub,
                                   producer=self.producer_id,
                                   seq=(1 << 40) | j)
        self._epoch = []

    def close(self) -> None:
        pass
