"""Partitioned durable log connector — the Kafka connector analog.

The reference's flagship connector is Kafka
(``flink-connectors/flink-connector-kafka``: ``KafkaSource`` FLIP-27 +
exactly-once ``KafkaSink`` with transactions).  No broker exists in this
environment, so the same *semantics* are provided against a local durable
partitioned log: N append-only partition files of CRC-framed FTB batches.

- :class:`PartitionedLog` — the "broker": append/read per partition, byte
  offsets are the consumer positions (Kafka offsets analog).
- :class:`LogSource` — FLIP-27 source: one split per partition, reader
  position = byte offset, checkpointed by the executor and resumed exactly
  (``KafkaSourceReader`` offset snapshot analog).  Bounded (read to current
  end) or unbounded (tail with polling).
- :class:`LogSink` — transactional sink (``KafkaSink`` EXACTLY_ONCE analog):
  batches buffer in memory per epoch; ``snapshot_state`` stages them as a
  transaction in the checkpoint; ``notify_checkpoint_complete`` appends to
  the log and records the committed transaction id in a sidecar, so a
  restore never double-commits (two-phase commit protocol,
  ``TwoPhaseCommitSinkFunction.java`` analog).
"""

from __future__ import annotations

import json
import os
import struct
import time
from dataclasses import dataclass
from typing import Any, Dict, Iterator, List, Optional

import numpy as np

from flink_tpu.connectors.sources import Source, SourceSplit
from flink_tpu.core.batch import RecordBatch, StreamElement

_FRAME = struct.Struct("<II")  # payload_len, crc32


class PartitionedLog:
    """Local durable partitioned log of RecordBatches."""

    def __init__(self, directory: str, num_partitions: int = 1):
        self.directory = directory
        os.makedirs(directory, exist_ok=True)
        meta = os.path.join(directory, "_meta.json")
        if os.path.exists(meta):
            with open(meta) as f:
                self.num_partitions = json.load(f)["num_partitions"]
        else:
            self.num_partitions = num_partitions
            with open(meta, "w") as f:
                json.dump({"num_partitions": num_partitions}, f)

    def _path(self, partition: int) -> str:
        return os.path.join(self.directory, f"partition-{partition:04d}.log")

    @staticmethod
    def exists(directory: str) -> bool:
        return os.path.exists(os.path.join(directory, "_meta.json"))

    def append(self, partition: int, batch: RecordBatch) -> int:
        """Append one batch; returns the end offset after the write."""
        from flink_tpu.formats import write_frame
        from flink_tpu.native.codec import encode_batch

        with open(self._path(partition), "ab") as f:
            write_frame(f, encode_batch(batch))
            f.flush()
            os.fsync(f.fileno())
            return f.tell()

    def end_offset(self, partition: int) -> int:
        p = self._path(partition)
        return os.path.getsize(p) if os.path.exists(p) else 0

    def read_from(self, partition: int, offset: int):
        """Yield ``(batch, next_offset)`` from ``offset`` to current end."""
        from flink_tpu.formats import read_frames
        from flink_tpu.native.codec import decode_batch

        p = self._path(partition)
        if not os.path.exists(p):
            return
        for payload, next_off in read_frames(p, offset):
            yield decode_batch(payload), next_off


class _LogSplitReader:
    """Reader for one partition; ``position`` = committed byte offset."""

    def __init__(self, log: PartitionedLog, partition: int, position: int,
                 bounded: bool, poll_interval_ms: int, idle_timeout_ms: int):
        self.log = log
        self.partition = partition
        self.position = int(position)
        self.bounded = bounded
        self.poll_interval_ms = poll_interval_ms
        self.idle_timeout_ms = idle_timeout_ms
        self._gen = self._run()

    def _run(self) -> Iterator[StreamElement]:
        idle_since = time.monotonic()
        while True:
            got = False
            for batch, next_off in self.log.read_from(self.partition, self.position):
                self.position = next_off
                got = True
                idle_since = time.monotonic()
                yield batch
            if self.bounded:
                return
            if not got:
                if (self.idle_timeout_ms and (time.monotonic() - idle_since)
                        * 1000 > self.idle_timeout_ms):
                    return
                time.sleep(self.poll_interval_ms / 1000.0)
                # yield control to the executor: an idle partition must not
                # starve the other splits' round-robin (empty batches route
                # harmlessly); also lets wall/record budgets + checkpoints run
                yield RecordBatch({})

    def __iter__(self):
        return self

    def __next__(self) -> StreamElement:
        return next(self._gen)


class LogSource(Source):
    """FLIP-27 source over a PartitionedLog: one split per partition."""

    def __init__(self, directory: str, bounded: bool = True,
                 poll_interval_ms: int = 20, idle_timeout_ms: int = 0):
        self.directory = directory
        self.bounded = bounded
        self.poll_interval_ms = poll_interval_ms
        self.idle_timeout_ms = idle_timeout_ms

    def create_splits(self, parallelism: int) -> List[SourceSplit]:
        if not PartitionedLog.exists(self.directory):
            # a typo'd path must fail loudly, not create an empty log and
            # run a successful empty job
            raise FileNotFoundError(
                f"LogSource: no partitioned log at {self.directory!r}")
        log = PartitionedLog(self.directory)
        return [LogSplit(self, p, log.num_partitions, partition=p)
                for p in range(log.num_partitions)]

    def open_split(self, split: "LogSplit",
                   position: Optional[int]) -> _LogSplitReader:
        return _LogSplitReader(PartitionedLog(self.directory), split.partition,
                               position or 0, self.bounded,
                               self.poll_interval_ms, self.idle_timeout_ms)


@dataclass
class LogSplit(SourceSplit):
    partition: int = 0

    @property
    def split_id(self) -> str:
        return f"partition-{self.partition}"

    def read(self) -> Iterator[StreamElement]:
        return self.source.open_split(self, 0)


class LogSink:
    """Exactly-once transactional sink into a PartitionedLog.

    Partitioning: ``hash(key_column) % num_partitions`` when a key column is
    given, else round-robin per batch.

    Parallel use: the runtime CLONES this sink per subtask
    (``clone_per_subtask``) — every instance gets its own attempt id, epoch
    buffer, and commit sidecar, so per-subtask barriers stage disjoint
    transactions.
    """

    clone_per_subtask = True

    def __init__(self, directory: str, num_partitions: int = 1,
                 key_column: Optional[str] = None, txn_id: str = "logsink"):
        import uuid

        self.log = PartitionedLog(directory, num_partitions)
        self.key_column = key_column
        self.txn_id = txn_id
        #: unique per sink attempt; committed-txn dedup keys on
        #: (attempt, cid), so a FRESH job writing to a directory with a
        #: stale sidecar never mistakes its own new txns for committed ones.
        #: A restore adopts the snapshot's attempt (see restore_state).
        self._attempt = uuid.uuid4().hex[:12]
        self._epoch: List[RecordBatch] = []
        self._staged: Dict[int, List[RecordBatch]] = {}
        self._rr = 0
        self.directory = directory
        # a crashed predecessor may have left a half-appended transaction
        self._recover_partial_commits()

    @property
    def _commits_path(self) -> str:
        # per-ATTEMPT sidecar: parallel clones and restored instances never
        # read-modify-write one shared file
        return os.path.join(self.directory,
                            f"_commits-{self.txn_id}-{self._attempt}.json")

    def _txn_lock(self):
        """Exclusive cross-process/thread lock for commit + recovery critical
        sections: sibling subtask clones share the directory, and recovery
        must never observe (or truncate under) a sibling's in-flight commit."""
        import fcntl
        from contextlib import contextmanager

        @contextmanager
        def lock():
            fd = os.open(os.path.join(self.directory, "_txnlock"),
                         os.O_CREAT | os.O_RDWR)
            try:
                fcntl.flock(fd, fcntl.LOCK_EX)
                yield
            finally:
                os.close(fd)  # releases the flock

        return lock()

    def on_cloned(self) -> None:
        """Fresh identity for a per-subtask clone."""
        import uuid

        self._attempt = uuid.uuid4().hex[:12]
        self._epoch = []
        self._staged = {}

    def _committed_ids(self) -> List[str]:
        """UNION over every attempt's sidecar (+ the legacy shared file):
        recovery decisions must see commits recorded by ANY prior attempt or
        sibling — keys are attempt-qualified, so the union never collides."""
        out: List[str] = []
        try:
            names = os.listdir(self.directory)
        except FileNotFoundError:
            return out
        prefix = f"_commits-{self.txn_id}"
        for f in names:
            if f.startswith(prefix) and f.endswith(".json"):
                try:
                    with open(os.path.join(self.directory, f)) as fh:
                        out.extend(json.load(fh))
                except (OSError, ValueError):
                    continue
        return out

    def _commit_key(self, checkpoint_id: int) -> str:
        return f"{self._attempt}:{checkpoint_id}"

    def _record_commit(self, checkpoint_id: int) -> None:
        # write ONLY this attempt's keys into its own sidecar (reads union
        # all attempts): mixing the union in would evict other attempts'
        # keys in arbitrary order once the 100-entry bound is hit
        own: List[str] = []
        if os.path.exists(self._commits_path):
            try:
                with open(self._commits_path) as f:
                    own = json.load(f)
            except (OSError, ValueError):
                own = []
        own.append(self._commit_key(checkpoint_id))
        tmp = self._commits_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(own[-100:], f)
        os.replace(tmp, self._commits_path)

    # -- Sink interface ------------------------------------------------------
    def write_batch(self, batch: RecordBatch) -> None:
        if len(batch):
            self._epoch.append(batch)

    def flush(self) -> None:
        # bounded end: no more barriers will come. ORDER MATTERS: staged
        # (older) transactions must land in the log BEFORE the final epoch's
        # rows, or consumers reading "last value per key" see stale data
        for cid in sorted(self._staged):
            self._commit(cid)
        for b in self._epoch:
            self._append(b)
        self._epoch = []

    def close(self) -> None:
        pass

    def _append(self, batch: RecordBatch) -> None:
        from flink_tpu.core.keygroups import hash_keys

        n_p = self.log.num_partitions
        if self.key_column is None or n_p == 1:
            self.log.append(self._rr % n_p, batch)
            self._rr += 1
            return
        # stable hash (process-seeded builtins would reshuffle key->partition
        # affinity across restarts, breaking per-key ordering)
        keys = np.asarray(batch.column(self.key_column))
        parts = (np.abs(hash_keys(keys).astype(np.int64)) % n_p).astype(np.int32)
        for p in np.unique(parts).tolist():
            self.log.append(int(p), batch.select(parts == p))

    # -- two-phase commit ----------------------------------------------------
    def snapshot_state(self) -> Dict[str, Any]:
        """Pre-commit: stage this epoch's batches under the NEXT barrier id
        (the executor calls snapshot then notify with the same id)."""
        staged_now = self._epoch
        self._epoch = []
        self._staged_counter = getattr(self, "_staged_counter", 0) + 1
        self._staged[self._staged_counter] = staged_now
        return {"staged": dict(self._staged), "counter": self._staged_counter,
                "attempt": self._attempt}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._recover_partial_commits()
        # adopt the snapshot's attempt: its committed txn keys must match
        self._attempt = snap.get("attempt", self._attempt)
        self._staged_counter = int(snap.get("counter", 0))
        committed = set(self._committed_ids())
        self._staged = {}
        for cid, batches in snap.get("staged", {}).items():
            cid = int(cid)
            if self._commit_key(cid) in committed:
                continue  # already in the log: never double-append
            self._staged[cid] = list(batches)
        # transactions staged in a completed checkpoint are owed to the log
        for cid in sorted(self._staged):
            self._commit(cid)

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        for cid in sorted(self._staged):
            self._commit(cid)

    # -- atomic commit protocol ---------------------------------------------
    # A commit writes an *intent* file (txn id + current end offsets) before
    # appending, and removes it after the sidecar records the commit. A crash
    # mid-append leaves the intent behind; recovery truncates each partition
    # back to the intent offsets and the restore re-appends the whole txn —
    # the log never holds a half transaction (2PC with rollback, the
    # ``TwoPhaseCommitSinkFunction`` recoverAndAbort analog).

    def _intent_path(self, cid: int) -> str:
        return os.path.join(self.log.directory,
                            f"_intent-{self.txn_id}-{self._attempt}-{cid}.json")

    def _recover_partial_commits(self) -> None:
        with self._txn_lock():
            committed = set(self._committed_ids())
            for f in os.listdir(self.log.directory):
                if not f.startswith(f"_intent-{self.txn_id}-"):
                    continue
                path = os.path.join(self.log.directory, f)
                try:
                    with open(path) as fh:
                        intent = json.load(fh)
                except (FileNotFoundError, ValueError):
                    continue  # sibling recovered it concurrently
                if intent["key"] not in committed:
                    for p_str, off in intent["offsets"].items():
                        lp = self.log._path(int(p_str))
                        if os.path.exists(lp) and os.path.getsize(lp) > off:
                            with open(lp, "r+b") as lf:
                                lf.truncate(off)
                try:
                    os.remove(path)
                except FileNotFoundError:
                    pass

    def _commit(self, cid: int) -> None:
        batches = self._staged.pop(cid, None)
        if batches is None or self._commit_key(cid) in self._committed_ids():
            return
        if not batches:
            self._record_commit(cid)
            return
        # the whole intent->append->record->cleanup sequence runs under the
        # directory txn lock so a sibling's recovery can never truncate a
        # half-appended transaction that is actually in progress
        with self._txn_lock():
            offsets = {p: self.log.end_offset(p)
                       for p in range(self.log.num_partitions)}
            tmp = self._intent_path(cid) + ".tmp"
            with open(tmp, "w") as f:
                json.dump({"key": self._commit_key(cid), "offsets": offsets},
                          f)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, self._intent_path(cid))
            for b in batches:
                self._append(b)
            self._record_commit(cid)
            try:
                os.remove(self._intent_path(cid))
            except FileNotFoundError:
                pass
