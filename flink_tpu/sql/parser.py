"""SQL lexer + AST + recursive-descent parser.

The reference parses SQL with Calcite (``flink-table/flink-sql-parser/``,
grammar templates) into ``SqlNode`` trees validated by the Blink planner
(``PlannerBase.scala:155``).  This is a self-contained parser for the
streaming-SQL dialect subset the framework executes: SELECT with expressions,
WHERE, GROUP BY (including the group-window functions ``TUMBLE``/``HOP``/
``SESSION`` of ``StreamExecGroupWindowAggregate.java:103``), HAVING,
ORDER BY / LIMIT (bounded results), aggregates, CASE, CAST, BETWEEN, IN,
LIKE, and INTERVAL/DATE/TIMESTAMP literals.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Tuple


class SqlParseError(ValueError):
    pass


# ---------------------------------------------------------------------------
# AST
# ---------------------------------------------------------------------------

class Expr:
    __slots__ = ()


@dataclass(frozen=True)
class Literal(Expr):
    value: object  # int | float | str | bool | None


@dataclass(frozen=True)
class Column(Expr):
    name: str
    #: optional table/alias qualifier ("a.k" -> Column("k", table="a"))
    table: Optional[str] = None


@dataclass(frozen=True)
class Star(Expr):
    pass


@dataclass(frozen=True)
class Unary(Expr):
    op: str  # '-', 'NOT'
    operand: Expr


@dataclass(frozen=True)
class Binary(Expr):
    op: str  # + - * / % || = <> < <= > >= AND OR
    left: Expr
    right: Expr


@dataclass(frozen=True)
class Call(Expr):
    name: str  # uppercased
    args: Tuple[Expr, ...]
    distinct: bool = False


@dataclass(frozen=True)
class Cast(Expr):
    expr: Expr
    type_name: str


@dataclass(frozen=True)
class Case(Expr):
    whens: Tuple[Tuple[Expr, Expr], ...]
    default: Optional[Expr]


@dataclass(frozen=True)
class Between(Expr):
    expr: Expr
    lo: Expr
    hi: Expr
    negated: bool = False


@dataclass(frozen=True)
class InList(Expr):
    expr: Expr
    items: Tuple[Expr, ...]
    negated: bool = False


@dataclass(frozen=True)
class Like(Expr):
    expr: Expr
    pattern: str
    negated: bool = False


@dataclass(frozen=True)
class IsNull(Expr):
    expr: Expr
    negated: bool = False


@dataclass(frozen=True)
class Interval(Expr):
    """Time interval, normalized to milliseconds."""

    ms: int


@dataclass(frozen=True)
class OverCall(Expr):
    """Window function call: ``fn(args) OVER (PARTITION BY p ORDER BY o
    [DESC] [frame])`` — the ``StreamExecRank`` shape (ROW_NUMBER in a Top-N
    subquery) and the ``StreamExecOverAggregate`` shape (SUM/COUNT/AVG/MIN/
    MAX over a partition).  Frame: both bounds None = RANGE UNBOUNDED
    PRECEDING (the SQL default when ORDER BY is present); ``frame_rows`` =
    ROWS n PRECEDING AND CURRENT ROW; ``frame_range_ms`` = RANGE INTERVAL
    n PRECEDING AND CURRENT ROW."""

    func: str
    partition_by: Optional[Expr]
    order_by: Optional[Expr]
    ascending: bool = True
    args: Tuple[Expr, ...] = ()
    frame_rows: Optional[int] = None
    frame_range_ms: Optional[int] = None
    #: ROWS frames are per-row; RANGE frames include peer rows (same order
    #: value) — matters only for unbounded frames with duplicate timestamps
    frame_is_rows: bool = False
    distinct: bool = False


@dataclass
class UnionStmt:
    """``SELECT ... UNION [ALL] SELECT ...`` chain; trailing ORDER BY/LIMIT
    bind to the whole union (standard SQL)."""

    parts: List["SelectStmt"]
    alls: List[bool]                     # one per UNION keyword
    order_by: List[Tuple["Expr", bool]]
    limit: Optional[int] = None


@dataclass
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass
class JoinClause:
    table: str
    alias: Optional[str]
    kind: str          # inner / left / right / full
    on: Expr
    #: set by the rewrite stage (rules.filter_pushdown): predicate applied
    #: to THIS input before the join (bare column names)
    pre_filter: Optional[Expr] = None
    #: ``JOIN t FOR SYSTEM_TIME AS OF <expr>``: temporal (versioned-table)
    #: or lookup (dimension) join — the time attribute of the LEFT row at
    #: which the right side is observed (``SqlSnapshot`` /
    #: ``StreamExecTemporalJoin`` / ``StreamExecLookupJoin``)
    system_time_of: Optional[Expr] = None


@dataclass
class MatchStage:
    """One PATTERN variable with its regex quantifier."""

    var: str
    quant_min: int = 1
    quant_max: Optional[int] = 1   # None = unbounded (+ / *)
    optional: bool = False         # ? or *


@dataclass
class MatchRecognizeClause:
    """``MATCH_RECOGNIZE (PARTITION BY .. ORDER BY .. MEASURES ..
    [ONE ROW PER MATCH] [AFTER MATCH SKIP ..] PATTERN (..)
    [WITHIN INTERVAL ..] DEFINE ..)`` — the row-pattern clause of
    ``SqlMatchRecognize`` (``flink-sql-parser``), lowered onto the CEP NFA
    (``StreamExecMatch.java:90``)."""

    partition_by: List[str]
    order_by: str
    measures: List[SelectItem]
    pattern: List[MatchStage]
    defines: dict                       # var -> Expr
    after_match: str = "skip_to_next"   # skip_to_next | skip_past_last
    within_ms: Optional[int] = None
    alias: Optional[str] = None


@dataclass
class SelectStmt:
    items: List[SelectItem]
    table: Optional[str]
    table_alias: Optional[str] = None
    #: FROM <table> MATCH_RECOGNIZE ( ... ): row-pattern recognition
    match: Optional["MatchRecognizeClause"] = None
    joins: List["JoinClause"] = field(default_factory=list)
    where: Optional[Expr] = None
    group_by: List[Expr] = field(default_factory=list)
    having: Optional[Expr] = None
    order_by: List[Tuple[Expr, bool]] = field(default_factory=list)  # (expr, asc)
    limit: Optional[int] = None
    #: rewrite-stage annotations (rules.py): predicate pushed onto the base
    #: scan, and the pruned column set the scan should project to
    scan_filter: Optional[Expr] = None
    scan_columns: Optional[Tuple[str, ...]] = None
    #: cost-stage annotation (cost.py join_reorder): chosen join order +
    #: estimated cost; also the done-marker so the rule runs once
    join_order_cost: Optional[str] = None


@dataclass
class ColumnDef:
    name: str
    type_name: str                      # normalized SQL type text


@dataclass
class CreateTableStmt:
    """``CREATE TABLE t (col TYPE, ..., [WATERMARK FOR c AS c - INTERVAL
    ...,] [PRIMARY KEY (c) NOT ENFORCED]) WITH ('connector'='...', ...)`` —
    the ``SqlCreateTable`` shape (``flink-sql-parser/.../ddl/
    SqlCreateTable.java``)."""

    name: str
    columns: List[ColumnDef]
    properties: dict                    # the WITH map, lower-cased keys
    watermark_column: Optional[str] = None
    watermark_delay_ms: int = 0
    primary_key: Optional[str] = None
    if_not_exists: bool = False


@dataclass
class CreateViewStmt:
    name: str
    query: object                       # SelectStmt | UnionStmt
    if_not_exists: bool = False


@dataclass
class DropStmt:
    kind: str                           # 'TABLE' | 'VIEW'
    name: str
    if_exists: bool = False


@dataclass
class ShowTablesStmt:
    pass


@dataclass
class DescribeStmt:
    name: str


#: aggregate function names the planner splits out of expressions
AGG_FUNCS = {"SUM", "COUNT", "AVG", "MIN", "MAX"}
#: group-window functions (GROUP BY position)
WINDOW_FUNCS = {"TUMBLE", "HOP", "SESSION"}
#: auxiliary window accessors (SELECT position)
WINDOW_AUX = {
    "TUMBLE_START", "TUMBLE_END", "TUMBLE_ROWTIME", "TUMBLE_PROCTIME",
    "HOP_START", "HOP_END", "HOP_ROWTIME",
    "SESSION_START", "SESSION_END", "SESSION_ROWTIME",
    "WINDOW_START", "WINDOW_END",
}

_UNIT_MS = {
    "MILLISECOND": 1, "MILLISECONDS": 1,
    "SECOND": 1000, "SECONDS": 1000,
    "MINUTE": 60_000, "MINUTES": 60_000,
    "HOUR": 3_600_000, "HOURS": 3_600_000,
    "DAY": 86_400_000, "DAYS": 86_400_000,
}

_KEYWORDS = {
    "SELECT", "FROM", "WHERE", "GROUP", "BY", "HAVING", "ORDER", "ASC",
    "DESC", "LIMIT", "AS", "AND", "OR", "NOT", "IN", "BETWEEN", "LIKE",
    "IS", "NULL", "TRUE", "FALSE", "CASE", "WHEN", "THEN", "ELSE", "END",
    "CAST", "INTERVAL", "DATE", "TIMESTAMP", "DISTINCT",
    "JOIN", "INNER", "LEFT", "RIGHT", "FULL", "OUTER", "ON",
    "OVER", "PARTITION", "UNION", "ALL",
}
# NOTE: the OVER frame words (ROWS/RANGE/PRECEDING/UNBOUNDED/CURRENT/ROW)
# are deliberately NOT keywords — they are non-reserved in standard SQL and
# are matched contextually inside OVER(...) (Parser.at_word), so they remain
# usable as column names.

_TOKEN_RE = re.compile(r"""
    \s+
  | --[^\n]*
  | (?P<number>\d+(\.\d*)?([eE][+-]?\d+)?|\.\d+)
  | (?P<string>'(?:[^']|'')*')
  | (?P<ident>[A-Za-z_][A-Za-z_0-9]*)
  | (?P<qident>"[^"]+"|`[^`]+`)
  | (?P<op><>|!=|<=|>=|\|\||[-+*/%(),.<>=?{}])
""", re.VERBOSE)


@dataclass(frozen=True)
class Token:
    kind: str   # NUMBER STRING IDENT KEYWORD OP EOF
    value: str
    pos: int


def tokenize(sql: str) -> List[Token]:
    out: List[Token] = []
    pos = 0
    n = len(sql)
    while pos < n:
        m = _TOKEN_RE.match(sql, pos)
        if not m:
            raise SqlParseError(f"unexpected character {sql[pos]!r} at {pos}")
        if m.lastgroup == "number":
            out.append(Token("NUMBER", m.group("number"), pos))
        elif m.lastgroup == "string":
            raw = m.group("string")[1:-1].replace("''", "'")
            out.append(Token("STRING", raw, pos))
        elif m.lastgroup == "ident":
            text = m.group("ident")
            up = text.upper()
            out.append(Token("KEYWORD" if up in _KEYWORDS else "IDENT",
                             up if up in _KEYWORDS else text, pos))
        elif m.lastgroup == "qident":
            out.append(Token("IDENT", m.group("qident")[1:-1], pos))
        elif m.lastgroup == "op":
            out.append(Token("OP", m.group("op"), pos))
        pos = m.end()
    out.append(Token("EOF", "", pos))
    return out


class Parser:
    def __init__(self, sql: str):
        self.tokens = tokenize(sql)
        self.i = 0

    # -- token helpers ------------------------------------------------------
    def peek(self) -> Token:
        return self.tokens[self.i]

    def next(self) -> Token:
        t = self.tokens[self.i]
        self.i += 1
        return t

    def accept(self, kind: str, value: Optional[str] = None) -> Optional[Token]:
        t = self.peek()
        if t.kind == kind and (value is None or t.value == value):
            return self.next()
        return None

    def expect(self, kind: str, value: Optional[str] = None) -> Token:
        t = self.accept(kind, value)
        if t is None:
            got = self.peek()
            raise SqlParseError(
                f"expected {value or kind}, got {got.value or got.kind!r} "
                f"at {got.pos}")
        return t

    def at_keyword(self, *kws: str) -> bool:
        t = self.peek()
        return t.kind == "KEYWORD" and t.value in kws

    # -- entry --------------------------------------------------------------
    def parse_statement(self):
        """SELECT or a UNION [ALL] chain of SELECTs."""
        stmt = self.parse_union_chain()
        self.expect("EOF")
        return stmt

    def parse_any(self):
        """Query OR DDL statement (``executeSql`` dispatch surface)."""
        if self.at_word("CREATE"):
            return self.parse_create()
        if self.at_word("DROP"):
            return self.parse_drop()
        if self.at_word("SHOW"):
            self.next()
            self.expect_word("TABLES")
            self.expect("EOF")
            return ShowTablesStmt()
        if self.at_word("DESCRIBE") or self.at_word("DESC"):
            self.next()
            name = self.expect("IDENT").value
            self.expect("EOF")
            return DescribeStmt(name)
        return self.parse_statement()

    # -- DDL ----------------------------------------------------------------
    def parse_create(self):
        self.expect_word("CREATE")
        self.accept_word("TEMPORARY")
        if self.accept_word("VIEW"):
            ine = self._if_not_exists()
            name = self.expect("IDENT").value
            self.expect("KEYWORD", "AS")
            query = self.parse_union_chain()
            self.expect("EOF")
            return CreateViewStmt(name, query, ine)
        self.expect_word("TABLE")
        ine = self._if_not_exists()
        name = self.expect("IDENT").value
        self.expect("OP", "(")
        cols: List[ColumnDef] = []
        wm_col, wm_delay = None, 0
        pkey = None
        while True:
            if self.accept_word("WATERMARK"):
                self.expect_word("FOR")
                wm_col = self.expect("IDENT").value
                self.expect("KEYWORD", "AS")
                e = self.parse_additive()
                # `c` (delay 0) or `c - INTERVAL 'n' UNIT`
                if isinstance(e, Binary) and e.op == "-" \
                        and isinstance(e.right, Interval):
                    wm_delay = e.right.ms
                elif not isinstance(e, Column):
                    raise SqlParseError(
                        "WATERMARK expression must be <col> or "
                        "<col> - INTERVAL '...' <unit>")
            elif self.at_word("PRIMARY"):
                self.next()
                self.expect_word("KEY")
                self.expect("OP", "(")
                pkey = self.expect("IDENT").value
                self.expect("OP", ")")
                if self.accept("KEYWORD", "NOT"):
                    self.expect_word("ENFORCED")
            else:
                cname = self.expect("IDENT").value
                cols.append(ColumnDef(cname, self._parse_type()))
            if not self.accept("OP", ","):
                break
        self.expect("OP", ")")
        self.expect_word("WITH")
        self.expect("OP", "(")
        props = {}
        while True:
            k = self.expect("STRING").value
            self.expect("OP", "=")
            props[k.lower()] = self.expect("STRING").value
            if not self.accept("OP", ","):
                break
        self.expect("OP", ")")
        self.expect("EOF")
        return CreateTableStmt(name, cols, props, wm_col, wm_delay, pkey, ine)

    def _parse_type(self) -> str:
        t = self.peek()
        if t.kind == "KEYWORD" and t.value == "TIMESTAMP":
            self.next()
            base = "TIMESTAMP"
        else:
            base = self.expect("IDENT").value.upper()
        if self.accept("OP", "("):
            args = [self.expect("NUMBER").value]
            while self.accept("OP", ","):
                args.append(self.expect("NUMBER").value)
            self.expect("OP", ")")
            base += f"({', '.join(args)})"
        return base

    def _if_not_exists(self) -> bool:
        if self.at_word("IF"):
            self.next()
            self.expect("KEYWORD", "NOT")
            self.expect_word("EXISTS")
            return True
        return False

    def parse_drop(self):
        self.expect_word("DROP")
        kind = "VIEW" if self.accept_word("VIEW") else None
        if kind is None:
            self.expect_word("TABLE")
            kind = "TABLE"
        ife = False
        if self.at_word("IF"):
            self.next()
            self.expect_word("EXISTS")
            ife = True
        name = self.expect("IDENT").value
        self.expect("EOF")
        return DropStmt(kind, name, ife)

    def parse_union_chain(self):
        left = self.parse_select(expect_eof=False)
        parts = [left]
        alls: List[bool] = []
        while self.accept("KEYWORD", "UNION"):
            alls.append(bool(self.accept("KEYWORD", "ALL")))
            parts.append(self.parse_select(expect_eof=False))
        if len(parts) == 1:
            return left
        # standard SQL: a trailing ORDER BY/LIMIT binds to the WHOLE union
        last = parts[-1]
        order_by, limit = list(last.order_by), last.limit
        last.order_by, last.limit = [], None
        for p in parts[:-1]:
            if p.order_by or p.limit is not None:
                raise SqlParseError(
                    "ORDER BY/LIMIT inside a UNION branch is not supported "
                    "(put them after the last SELECT)")
        return UnionStmt(parts=parts, alls=alls, order_by=order_by,
                         limit=limit)

    def parse_select(self, expect_eof: bool = True) -> SelectStmt:
        self.expect("KEYWORD", "SELECT")
        items = [self.parse_select_item()]
        while self.accept("OP", ","):
            items.append(self.parse_select_item())
        table = None
        table_alias = None
        match_clause = None
        joins: List[JoinClause] = []
        if self.accept("KEYWORD", "FROM"):
            if self.accept("OP", "("):
                table = self.parse_union_chain()
                self.expect("OP", ")")
            else:
                table = self.expect("IDENT").value
            if self.at_word("MATCH_RECOGNIZE"):
                match_clause = self.parse_match_recognize()
            elif self.accept("KEYWORD", "AS"):
                table_alias = self.expect("IDENT").value
            elif self.peek().kind == "IDENT":
                table_alias = self.next().value
            while self.at_keyword("JOIN", "INNER", "LEFT", "RIGHT", "FULL"):
                kind = "inner"
                if self.accept("KEYWORD", "INNER"):
                    pass
                elif self.accept("KEYWORD", "LEFT"):
                    kind = "left"
                    self.accept("KEYWORD", "OUTER")
                elif self.accept("KEYWORD", "RIGHT"):
                    kind = "right"
                    self.accept("KEYWORD", "OUTER")
                elif self.accept("KEYWORD", "FULL"):
                    kind = "full"
                    self.accept("KEYWORD", "OUTER")
                self.expect("KEYWORD", "JOIN")
                jt = self.expect("IDENT").value
                sys_time = None
                if self.accept_word("FOR"):
                    self.expect_word("SYSTEM_TIME")
                    self.expect("KEYWORD", "AS")
                    self.expect_word("OF")
                    sys_time = self.parse_additive()
                jalias = None
                if self.accept("KEYWORD", "AS"):
                    jalias = self.expect("IDENT").value
                elif self.peek().kind == "IDENT":
                    jalias = self.next().value
                self.expect("KEYWORD", "ON")
                on = self.parse_expr()
                joins.append(JoinClause(jt, jalias, kind, on,
                                        system_time_of=sys_time))
        stmt = SelectStmt(items=items, table=table, table_alias=table_alias,
                          joins=joins, match=match_clause)
        if self.accept("KEYWORD", "WHERE"):
            stmt.where = self.parse_expr()
        if self.accept("KEYWORD", "GROUP"):
            self.expect("KEYWORD", "BY")
            stmt.group_by.append(self.parse_expr())
            while self.accept("OP", ","):
                stmt.group_by.append(self.parse_expr())
        if self.accept("KEYWORD", "HAVING"):
            stmt.having = self.parse_expr()
        if self.accept("KEYWORD", "ORDER"):
            self.expect("KEYWORD", "BY")
            stmt.order_by.append(self.parse_order_item())
            while self.accept("OP", ","):
                stmt.order_by.append(self.parse_order_item())
        if self.accept("KEYWORD", "LIMIT"):
            stmt.limit = int(self.expect("NUMBER").value)
        if expect_eof:
            self.expect("EOF")
        return stmt

    def parse_order_item(self) -> Tuple[Expr, bool]:
        e = self.parse_expr()
        asc = True
        if self.accept("KEYWORD", "DESC"):
            asc = False
        else:
            self.accept("KEYWORD", "ASC")
        return (e, asc)

    def parse_select_item(self) -> SelectItem:
        if self.accept("OP", "*"):
            return SelectItem(Star())
        e = self.parse_expr()
        alias = None
        if self.accept("KEYWORD", "AS"):
            alias = self.expect("IDENT").value
        elif self.peek().kind == "IDENT":
            alias = self.next().value
        return SelectItem(e, alias)

    # -- expressions (precedence climbing) ----------------------------------
    def parse_expr(self) -> Expr:
        return self.parse_or()

    def parse_or(self) -> Expr:
        e = self.parse_and()
        while self.accept("KEYWORD", "OR"):
            e = Binary("OR", e, self.parse_and())
        return e

    def parse_and(self) -> Expr:
        e = self.parse_not()
        while self.accept("KEYWORD", "AND"):
            e = Binary("AND", e, self.parse_not())
        return e

    def parse_not(self) -> Expr:
        if self.accept("KEYWORD", "NOT"):
            return Unary("NOT", self.parse_not())
        return self.parse_predicate()

    def parse_predicate(self) -> Expr:
        e = self.parse_additive()
        negated = bool(self.accept("KEYWORD", "NOT"))
        if self.accept("KEYWORD", "BETWEEN"):
            lo = self.parse_additive()
            self.expect("KEYWORD", "AND")
            hi = self.parse_additive()
            return Between(e, lo, hi, negated)
        if self.accept("KEYWORD", "IN"):
            self.expect("OP", "(")
            items = [self.parse_expr()]
            while self.accept("OP", ","):
                items.append(self.parse_expr())
            self.expect("OP", ")")
            return InList(e, tuple(items), negated)
        if self.accept("KEYWORD", "LIKE"):
            pat = self.expect("STRING").value
            return Like(e, pat, negated)
        if negated:
            raise SqlParseError("NOT must be followed by BETWEEN/IN/LIKE here")
        if self.accept("KEYWORD", "IS"):
            neg = bool(self.accept("KEYWORD", "NOT"))
            self.expect("KEYWORD", "NULL")
            return IsNull(e, neg)
        t = self.peek()
        if t.kind == "OP" and t.value in ("=", "<>", "!=", "<", "<=", ">", ">="):
            self.next()
            op = "<>" if t.value == "!=" else t.value
            return Binary(op, e, self.parse_additive())
        return e

    def parse_additive(self) -> Expr:
        e = self.parse_multiplicative()
        while True:
            t = self.peek()
            if t.kind == "OP" and t.value in ("+", "-", "||"):
                self.next()
                e = Binary(t.value, e, self.parse_multiplicative())
            else:
                return e

    def parse_multiplicative(self) -> Expr:
        e = self.parse_unary()
        while True:
            t = self.peek()
            if t.kind == "OP" and t.value in ("*", "/", "%"):
                self.next()
                e = Binary(t.value, e, self.parse_unary())
            else:
                return e

    def parse_unary(self) -> Expr:
        if self.accept("OP", "-"):
            return Unary("-", self.parse_unary())
        if self.accept("OP", "+"):
            return self.parse_unary()
        return self.parse_primary()

    def parse_primary(self) -> Expr:
        t = self.peek()
        if t.kind == "NUMBER":
            self.next()
            v = float(t.value) if ("." in t.value or "e" in t.value.lower()) \
                else int(t.value)
            return Literal(v)
        if t.kind == "STRING":
            self.next()
            return Literal(t.value)
        if self.accept("KEYWORD", "TRUE"):
            return Literal(True)
        if self.accept("KEYWORD", "FALSE"):
            return Literal(False)
        if self.accept("KEYWORD", "NULL"):
            return Literal(None)
        if self.accept("KEYWORD", "INTERVAL"):
            val = self.expect("STRING").value
            unit_tok = self.expect("IDENT")
            unit = unit_tok.value.upper()
            if unit not in _UNIT_MS:
                raise SqlParseError(f"unknown interval unit {unit!r}")
            return Interval(int(float(val) * _UNIT_MS[unit]))
        if self.accept("KEYWORD", "DATE"):
            return Literal(_date_to_ms(self.expect("STRING").value))
        if self.accept("KEYWORD", "TIMESTAMP"):
            return Literal(_timestamp_to_ms(self.expect("STRING").value))
        if self.accept("KEYWORD", "CAST"):
            self.expect("OP", "(")
            e = self.parse_expr()
            self.expect("KEYWORD", "AS")
            ty = self.expect("IDENT").value.upper()
            # swallow precision, e.g. DECIMAL(12, 2)
            if self.accept("OP", "("):
                while not self.accept("OP", ")"):
                    self.next()
            self.expect("OP", ")")
            return Cast(e, ty)
        if self.accept("KEYWORD", "CASE"):
            whens = []
            while self.accept("KEYWORD", "WHEN"):
                cond = self.parse_expr()
                self.expect("KEYWORD", "THEN")
                whens.append((cond, self.parse_expr()))
            default = None
            if self.accept("KEYWORD", "ELSE"):
                default = self.parse_expr()
            self.expect("KEYWORD", "END")
            return Case(tuple(whens), default)
        if self.accept("OP", "("):
            e = self.parse_expr()
            self.expect("OP", ")")
            return e
        if t.kind == "IDENT":
            self.next()
            name = t.value
            if self.accept("OP", "("):
                call = self.parse_call(name)
                if self.at_keyword("OVER"):
                    return self.parse_over(call)
                return call
            # qualified column: tbl.col keeps its qualifier (join resolution)
            qualifier = None
            while self.accept("OP", "."):
                qualifier = name if qualifier is None else f"{qualifier}.{name}"
                name = self.expect("IDENT").value
            return Column(name, table=qualifier)
        raise SqlParseError(f"unexpected token {t.value or t.kind!r} at {t.pos}")

    def parse_match_recognize(self) -> MatchRecognizeClause:
        """``MATCH_RECOGNIZE ( ... ) [AS alias]`` — clause words are
        contextual (IDENT tokens), matching Calcite's non-reserved
        treatment, so MEASURES/PATTERN/DEFINE stay usable as column
        names elsewhere."""
        self.expect_word("MATCH_RECOGNIZE")
        self.expect("OP", "(")
        partition_by: List[str] = []
        order_by = None
        measures: List[SelectItem] = []
        after_match = "skip_to_next"
        pattern: List[MatchStage] = []
        defines: dict = {}
        within_ms = None
        if self.accept("KEYWORD", "PARTITION"):
            self.expect("KEYWORD", "BY")
            partition_by.append(self.expect("IDENT").value)
            while self.accept("OP", ","):
                partition_by.append(self.expect("IDENT").value)
        if self.accept("KEYWORD", "ORDER"):
            self.expect("KEYWORD", "BY")
            order_by = self.expect("IDENT").value
            self.accept("KEYWORD", "ASC")
        if order_by is None:
            raise SqlParseError("MATCH_RECOGNIZE requires ORDER BY")
        if self.accept_word("MEASURES"):
            measures.append(self.parse_select_item())
            while self.accept("OP", ","):
                measures.append(self.parse_select_item())
        if self.accept_word("ONE"):
            self.expect_word("ROW")
            self.expect_word("PER")
            self.expect_word("MATCH")
        elif self.accept("KEYWORD", "ALL"):
            raise SqlParseError("ALL ROWS PER MATCH is not supported "
                                "(use ONE ROW PER MATCH)")
        if self.accept_word("AFTER"):
            self.expect_word("MATCH")
            self.expect_word("SKIP")
            if self.accept_word("PAST"):
                self.expect_word("LAST")
                self.expect_word("ROW")
                after_match = "skip_past_last"
            elif self.accept_word("TO"):
                self.expect_word("NEXT")
                self.expect_word("ROW")
                after_match = "skip_to_next"
            else:
                raise SqlParseError("AFTER MATCH SKIP must be PAST LAST ROW "
                                    "or TO NEXT ROW")
        self.expect_word("PATTERN")
        self.expect("OP", "(")
        while not self.accept("OP", ")"):
            var = self.expect("IDENT").value
            st = MatchStage(var)
            if self.accept("OP", "+"):
                st = MatchStage(var, 1, None)
            elif self.accept("OP", "*"):
                st = MatchStage(var, 1, None, optional=True)
            elif self.accept("OP", "?"):
                st = MatchStage(var, 1, 1, optional=True)
            elif self.accept("OP", "{"):
                lo = int(self.expect("NUMBER").value)
                hi = lo
                if self.accept("OP", ","):
                    # {n,} = at least n; {n,m} = between n and m
                    hi = (int(self.next().value)
                          if self.peek().kind == "NUMBER" else None)
                self.expect("OP", "}")
                st = MatchStage(var, lo, hi)
            pattern.append(st)
        if not pattern:
            raise SqlParseError("PATTERN must name at least one variable")
        if self.accept_word("WITHIN"):
            e = self.parse_primary()
            if not isinstance(e, Interval):
                raise SqlParseError("WITHIN takes INTERVAL '...' <unit>")
            within_ms = e.ms
        self.expect_word("DEFINE")
        while True:
            var = self.expect("IDENT").value
            self.expect("KEYWORD", "AS")
            defines[var.upper()] = self.parse_expr()
            if not self.accept("OP", ","):
                break
        self.expect("OP", ")")
        alias = None
        if self.accept("KEYWORD", "AS"):
            alias = self.expect("IDENT").value
        elif self.peek().kind == "IDENT":
            alias = self.next().value
        return MatchRecognizeClause(
            partition_by=partition_by, order_by=order_by, measures=measures,
            pattern=pattern, defines=defines, after_match=after_match,
            within_ms=within_ms, alias=alias)

    def parse_over(self, call: Expr) -> "OverCall":
        self.expect("KEYWORD", "OVER")
        self.expect("OP", "(")
        partition = order = None
        asc = True
        frame_rows = frame_range_ms = None
        if self.accept("KEYWORD", "PARTITION"):
            self.expect("KEYWORD", "BY")
            partition = self.parse_expr()
        if self.accept("KEYWORD", "ORDER"):
            self.expect("KEYWORD", "BY")
            order = self.parse_expr()
            if self.accept("KEYWORD", "DESC"):
                asc = False
            else:
                self.accept("KEYWORD", "ASC")
        is_rows = False
        if self.at_word("ROWS") or self.at_word("RANGE"):
            frame_rows, frame_range_ms, is_rows = self.parse_frame()
        self.expect("OP", ")")
        if not isinstance(call, Call):
            raise SqlParseError("OVER must follow a function call")
        return OverCall(call.name, partition, order, asc,
                        args=call.args, frame_rows=frame_rows,
                        frame_range_ms=frame_range_ms, frame_is_rows=is_rows,
                        distinct=call.distinct)

    # frame words are contextual (IDENT tokens), not reserved keywords
    def at_word(self, word: str) -> bool:
        t = self.peek()
        return t.kind == "IDENT" and t.value.upper() == word

    def accept_word(self, word: str) -> bool:
        if self.at_word(word):
            self.next()
            return True
        return False

    def expect_word(self, word: str) -> None:
        if not self.accept_word(word):
            t = self.peek()
            raise SqlParseError(
                f"expected {word} at {t.pos}, got {t.value or t.kind!r}")

    def parse_frame(self):
        """``ROWS|RANGE [BETWEEN] <bound> PRECEDING [AND CURRENT ROW]`` →
        (frame_rows, frame_range_ms, is_rows); UNBOUNDED → (None, None, _)."""
        is_rows = self.accept_word("ROWS")
        if not is_rows:
            self.expect_word("RANGE")
        self.accept("KEYWORD", "BETWEEN")
        frame_rows = frame_range_ms = None
        if self.accept_word("UNBOUNDED"):
            pass  # unbounded preceding = the default frame
        elif is_rows:
            t = self.expect("NUMBER")
            frame_rows = int(float(t.value))
        else:
            e = self.parse_primary()
            if not isinstance(e, Interval):
                raise SqlParseError(
                    "RANGE frame bound must be INTERVAL '...' PRECEDING")
            frame_range_ms = e.ms
        self.expect_word("PRECEDING")
        if self.accept("KEYWORD", "AND"):
            self.expect_word("CURRENT")
            self.expect_word("ROW")
        return frame_rows, frame_range_ms, is_rows

    def parse_call(self, name: str) -> Expr:
        up = name.upper()
        if self.accept("OP", ")"):
            return Call(up, ())
        if up == "COUNT" and self.accept("OP", "*"):
            self.expect("OP", ")")
            return Call("COUNT", (Star(),))
        distinct = bool(self.accept("KEYWORD", "DISTINCT"))
        args = [self.parse_expr()]
        while self.accept("OP", ","):
            args.append(self.parse_expr())
        self.expect("OP", ")")
        return Call(up, tuple(args), distinct)


def _date_to_ms(s: str) -> int:
    y, m, d = (int(x) for x in s.strip().split("-"))
    import datetime
    epoch = datetime.date(1970, 1, 1)
    return (datetime.date(y, m, d) - epoch).days * 86_400_000


def _timestamp_to_ms(s: str) -> int:
    import datetime
    s = s.strip()
    fmt = "%Y-%m-%d %H:%M:%S.%f" if "." in s else (
        "%Y-%m-%d %H:%M:%S" if " " in s else "%Y-%m-%d")
    dt = datetime.datetime.strptime(s, fmt).replace(
        tzinfo=datetime.timezone.utc)
    return int(dt.timestamp() * 1000)


def parse(sql: str):
    """-> SelectStmt | UnionStmt."""
    return Parser(sql.strip().rstrip(";")).parse_statement()


def parse_any(sql: str):
    """-> query statement OR a DDL statement (CREATE/DROP/SHOW/DESCRIBE)."""
    return Parser(sql.strip().rstrip(";")).parse_any()
