"""Logical rewrite stage: rule pipeline between parse and lowering.

The reference's Blink planner optimizes the logical plan with Calcite rule
sets + a cost model before producing physical nodes
(``PlannerBase.scala:155``, rules in
``flink-table-planner-blink/src/main/scala/.../plan/rules/``).  This module
is the analog for the direct-lowering planner: AST→AST rewrite rules applied
to a fixpoint, each recording its application so ``EXPLAIN`` can show the
optimized shape (VERDICT r2 missing #1).

Rules:
- ``union_associativity``   — mixed ``UNION``/``UNION ALL`` chains nest
  left-associatively into homogeneous unions (closes the mixed-chain gap).
- ``over_partition_split``  — a SELECT whose OVER windows use SEVERAL
  (PARTITION BY, ORDER BY) groups splits into nested SELECTs, one group per
  level (closes the multiple-OVER-partitionings gap).
- ``filter_pushdown``       — WHERE conjuncts referencing a single join
  input move to that input's pre-join filter; outer-query conjuncts over a
  derived table's pass-through columns push into the subquery.
- ``projection_prune``      — a derived table's SELECT list prunes to the
  columns the outer query references; base-table scans record the referenced
  column set so lowering projects early (``scan_columns``).
"""

from __future__ import annotations

from dataclasses import replace
from typing import Callable, Dict, List, Optional, Set, Tuple

from flink_tpu.sql.parser import (AGG_FUNCS, WINDOW_AUX, WINDOW_FUNCS, Binary,
                                  Call, Column, Expr, OverCall, SelectItem,
                                  SelectStmt, Star, UnionStmt)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _children(expr: Expr) -> List[Expr]:
    from flink_tpu.sql.parser import (Between, Case, Cast, InList, IsNull,
                                      Like, Unary)
    if isinstance(expr, Unary):
        return [expr.operand]
    if isinstance(expr, Binary):
        return [expr.left, expr.right]
    if isinstance(expr, Call):
        return list(expr.args)
    if isinstance(expr, OverCall):
        out = list(expr.args)
        if expr.partition_by is not None:
            out.append(expr.partition_by)
        if expr.order_by is not None:
            out.append(expr.order_by)
        return out
    if isinstance(expr, Cast):
        return [expr.expr]
    if isinstance(expr, Case):
        out = [x for pair in expr.whens for x in pair]
        if expr.default is not None:
            out.append(expr.default)
        return out
    if isinstance(expr, Between):
        return [expr.expr, expr.lo, expr.hi]
    if isinstance(expr, InList):
        return [expr.expr] + list(expr.items)
    if isinstance(expr, IsNull):
        return [expr.expr]
    if isinstance(expr, Like):
        return [expr.expr]
    return []


def _columns_of(expr: Optional[Expr]) -> List[Column]:
    if expr is None:
        return []
    if isinstance(expr, Column):
        return [expr]
    out: List[Column] = []
    for c in _children(expr):
        out.extend(_columns_of(c))
    return out


def _contains_agg_or_over(expr: Expr) -> bool:
    if isinstance(expr, OverCall):
        return True
    if isinstance(expr, Call) and expr.name in AGG_FUNCS:
        return True
    return any(_contains_agg_or_over(c) for c in _children(expr))


def _strip_qualifiers(expr: Expr) -> Expr:
    from flink_tpu.sql.planner import _transform
    return _transform(expr, lambda e: Column(e.name)
                      if isinstance(e, Column) and e.table is not None
                      else None)


def _conjuncts(expr: Expr) -> List[Expr]:
    if isinstance(expr, Binary) and expr.op.upper() == "AND":
        return _conjuncts(expr.left) + _conjuncts(expr.right)
    return [expr]


def _and_all(parts: List[Expr]) -> Optional[Expr]:
    if not parts:
        return None
    out = parts[0]
    for p in parts[1:]:
        out = Binary("AND", out, p)
    return out


def _referenced_names(stmt: SelectStmt) -> Optional[Set[str]]:
    """Unqualified column names the stmt references anywhere; None = all
    (a Star appears)."""
    names: Set[str] = set()
    exprs: List[Optional[Expr]] = [it.expr for it in stmt.items]
    exprs += [stmt.where, stmt.having]
    exprs += list(stmt.group_by)
    exprs += [e for e, _ in stmt.order_by]
    exprs += [j.on for j in stmt.joins]
    for e in exprs:
        if e is None:
            continue
        if isinstance(e, Star) or any(isinstance(c, Star)
                                      for c in _children(e)):
            return None
        for c in _columns_of(e):
            names.add(c.name)
    if any(isinstance(it.expr, Star) for it in stmt.items):
        return None
    return names


def _over_group(oc: OverCall):
    return (repr(oc.partition_by), repr(oc.order_by), oc.ascending)


def _collect_overs(expr: Expr, out: List[OverCall]) -> None:
    if isinstance(expr, OverCall):
        out.append(expr)
        return                      # OVER calls do not nest
    for c in _children(expr):
        _collect_overs(c, out)


def _replace_exprs(expr: Expr, mapping: Dict[Expr, Expr]) -> Expr:
    from flink_tpu.sql.planner import _transform
    return _transform(expr, lambda e: mapping.get(e))


# ---------------------------------------------------------------------------
# rules — each returns a rewritten stmt or None (no change)
# ---------------------------------------------------------------------------

def union_associativity(stmt, catalog) -> Optional[UnionStmt]:
    """``A UNION B UNION ALL C`` = ``(A UNION B) UNION ALL C`` (SQL
    left-associativity): restructure a MIXED flat chain into nested
    homogeneous unions the lowering already handles."""
    if not isinstance(stmt, UnionStmt) or len(set(stmt.alls)) <= 1:
        return None
    cur = stmt.parts[0]
    run = [cur]
    run_all = stmt.alls[0]

    def close(parts, is_all):
        if len(parts) == 1:
            return parts[0]
        return UnionStmt(parts=list(parts), alls=[is_all] * (len(parts) - 1),
                         order_by=[], limit=None)

    for part, is_all in zip(stmt.parts[1:], stmt.alls):
        if is_all == run_all:
            run.append(part)
        else:
            run = [close(run, run_all), part]
            run_all = is_all
    top = close(run, run_all)
    if isinstance(top, UnionStmt):
        top.order_by = stmt.order_by
        top.limit = stmt.limit
        return top
    # degenerate (single part): wrap to carry order/limit
    return UnionStmt(parts=[top], alls=[], order_by=stmt.order_by,
                     limit=stmt.limit)


def over_partition_split(stmt, catalog) -> Optional[SelectStmt]:
    """Multiple distinct (PARTITION BY, ORDER BY) OVER groups in one SELECT
    split into nested SELECTs: the innermost computes one group's aggregates
    as appended columns, the outer consumes them — repeat until one group
    per level (``StreamExecOverAggregate`` handles one ordering each)."""
    if not isinstance(stmt, SelectStmt) or stmt.group_by:
        return None
    overs: List[OverCall] = []
    for it in stmt.items:
        _collect_overs(it.expr, overs)
    groups: Dict[tuple, List[OverCall]] = {}
    for oc in overs:
        groups.setdefault(_over_group(oc), []).append(oc)
    if len(groups) <= 1:
        return None
    # innermost level computes the FIRST group; the (recursively rewritten)
    # outer level consumes its columns
    first_key = _over_group(overs[0])
    inner_items = [SelectItem(Star(), None)]
    mapping: Dict[Expr, Expr] = {}
    for i, oc in enumerate(groups[first_key]):
        name = f"__overg{i}"
        inner_items.append(SelectItem(oc, name))
        mapping[oc] = Column(name)
    inner = SelectStmt(items=inner_items, table=stmt.table,
                       table_alias=stmt.table_alias,
                       joins=stmt.joins, where=stmt.where)
    # the outer's FROM is an anonymous subquery: qualified references to
    # the original alias must become bare names (the subquery exposes
    # flat output columns)
    outer_items = [
        SelectItem(_strip_qualifiers(_replace_exprs(it.expr, mapping)),
                   it.alias)
        for it in stmt.items]
    outer_order = [(_strip_qualifiers(_replace_exprs(e, mapping)), asc)
                   for e, asc in stmt.order_by]
    return SelectStmt(items=outer_items, table=inner, table_alias=None,
                      joins=[], where=None, group_by=[],
                      having=stmt.having,   # preserved: lowering validates
                      order_by=outer_order, limit=stmt.limit)


def filter_pushdown(stmt, catalog) -> Optional[SelectStmt]:
    """WHERE conjuncts that reference exactly one join input move to that
    input's ``pre_filter`` (applied before the join); conjuncts over a
    derived table's pass-through output columns move into the subquery."""
    if not isinstance(stmt, SelectStmt) or stmt.where is None:
        return None
    # --- joins: per-input predicate extraction
    if stmt.joins and stmt.table in (catalog or {}):
        schemas: Dict[str, Set[str]] = {}
        base_alias = stmt.table_alias or stmt.table
        schemas[base_alias] = set(catalog[stmt.table].columns)
        # a WHERE predicate on a NULL-PRODUCING side of an outer join is
        # NOT equivalent pre-join (it would keep null-extended rows the
        # post-join filter removes): only non-null-producing inputs accept
        # pushdown — right inputs of INNER joins; the base/left chain when
        # no RIGHT/FULL join can null-extend it
        pushable_aliases: Set[str] = set()
        if all(j.kind in ("inner", "left") for j in stmt.joins):
            pushable_aliases.add(base_alias)
        for j in stmt.joins:
            if j.table in catalog:
                schemas[j.alias or j.table] = set(catalog[j.table].columns)
                if j.kind == "inner":
                    pushable_aliases.add(j.alias or j.table)
        remaining: List[Expr] = []
        pushed: Dict[str, List[Expr]] = {}
        for conj in _conjuncts(stmt.where):
            if _contains_agg_or_over(conj):
                remaining.append(conj)
                continue
            owners: Set[str] = set()
            ok = True
            for col in _columns_of(conj):
                if col.table is not None:
                    owners.add(col.table)
                else:
                    holders = [a for a, cols in schemas.items()
                               if col.name in cols]
                    if len(holders) == 1:
                        owners.add(holders[0])
                    else:
                        ok = False
                        break
            if ok and len(owners) == 1 and \
                    next(iter(owners)) in pushable_aliases:
                # the input stream pre-join carries BARE column names
                pushed.setdefault(owners.pop(), []).append(
                    _strip_qualifiers(conj))
            else:
                remaining.append(conj)
        if pushed:
            new_joins = []
            changed = False
            for j in stmt.joins:
                a = j.alias or j.table
                if a in pushed:
                    prior = [j.pre_filter] if j.pre_filter is not None else []
                    pre = _and_all(prior + pushed.pop(a))
                    new_joins.append(replace(j, pre_filter=pre))
                    changed = True
                else:
                    new_joins.append(j)
            base_pre = stmt.scan_filter
            if base_alias in pushed:
                base_pre = _and_all(
                    ([base_pre] if base_pre is not None else [])
                    + pushed.pop(base_alias))
                changed = True
            if changed:
                return replace(stmt, joins=new_joins,
                               where=_and_all(remaining),
                               scan_filter=base_pre)
        return None
    # --- derived table: push conjuncts over pass-through columns inside
    if isinstance(stmt.table, SelectStmt) and not stmt.joins:
        inner = stmt.table
        if inner.group_by or inner.having is not None or inner.limit \
                is not None or inner.order_by:
            return None
        if any(_contains_agg_or_over(it.expr) for it in inner.items):
            # filtering BELOW a window/aggregate computation changes its
            # input rows (running sums, ROW_NUMBER Top-N): not equivalent
            return None
        passthrough: Dict[str, Expr] = {}
        for it in inner.items:
            if isinstance(it.expr, Column) and it.expr.table is None:
                passthrough[it.alias or it.expr.name] = it.expr
        pushable: List[Expr] = []
        remaining = []
        for conj in _conjuncts(stmt.where):
            cols = _columns_of(conj)
            if (cols and not _contains_agg_or_over(conj)
                    and all(c.table is None and c.name in passthrough
                            for c in cols)):
                pushable.append(_replace_exprs(
                    conj, {Column(n): e for n, e in passthrough.items()}))
            else:
                remaining.append(conj)
        if not pushable:
            return None
        new_inner = replace(
            inner, where=_and_all(
                ([inner.where] if inner.where is not None else [])
                + pushable))
        return replace(stmt, table=new_inner, where=_and_all(remaining))
    return None


def projection_prune(stmt, catalog) -> Optional[SelectStmt]:
    """Prune a derived table's SELECT list to the outer query's referenced
    columns, and record the referenced column set on base-table scans so
    lowering projects before any operator (``scan_columns``)."""
    if not isinstance(stmt, SelectStmt):
        return None
    refs = _referenced_names(stmt)
    # --- derived table: prune inner items
    if isinstance(stmt.table, SelectStmt) and refs is not None:
        inner = stmt.table
        if not inner.order_by and not any(isinstance(it.expr, Star)
                                          for it in inner.items):
            from flink_tpu.sql.expressions import expr_name
            named = [(it.alias or expr_name(it.expr, i), it)
                     for i, it in enumerate(inner.items)]
            # fixpoint: a kept item's own expression may reference sibling
            # outputs (e.g. ROW_NUMBER() OVER (ORDER BY amount) keeps the
            # 'amount' item — the Top-N lowering reads it from the subquery)
            needed = set(refs)
            while True:
                extra = {c.name for nm, it in named if nm in needed
                         for c in _columns_of(it.expr)}
                if extra <= needed:
                    break
                needed |= extra
            keep = [it for nm, it in named if nm in needed]
            if keep and len(keep) < len(inner.items):
                return replace(stmt, table=replace(inner, items=keep))
    # --- base table: record the scan projection
    if (isinstance(stmt.table, str) and stmt.table in (catalog or {})
            and not stmt.joins and refs is not None
            and stmt.scan_columns is None):
        cols = [c for c in catalog[stmt.table].columns if c in refs]
        rowtime = getattr(catalog[stmt.table], "rowtime", None)
        if rowtime and rowtime not in cols \
                and rowtime in catalog[stmt.table].columns:
            cols.append(rowtime)
        if cols and len(cols) < len(catalog[stmt.table].columns):
            return replace(stmt, scan_columns=tuple(cols))
    return None


def _join_reorder(stmt, catalog):
    # cost stage lives in sql/cost.py; runs AFTER filter_pushdown so the
    # selectivity model sees the pushed per-input predicates
    from flink_tpu.sql.cost import join_reorder
    return join_reorder(stmt, catalog)


RULES: List[Tuple[str, Callable]] = [
    ("union_associativity", union_associativity),
    ("over_partition_split", over_partition_split),
    ("filter_pushdown", filter_pushdown),
    ("projection_prune", projection_prune),
    ("join_reorder(cost-based)", _join_reorder),
]


def apply_rules(stmt, catalog, applied: Optional[List[str]] = None,
                max_iters: int = 10):
    """Run the rule pipeline to a fixpoint (bounded).  ``applied`` collects
    rule names for EXPLAIN."""
    for _ in range(max_iters):
        changed = False
        for name, rule in RULES:
            new = rule(stmt, catalog)
            if new is not None:
                stmt = new
                changed = True
                if applied is not None:
                    applied.append(name)
        if not changed:
            break
    return stmt
