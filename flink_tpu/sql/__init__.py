"""SQL & Table API layer.

Stack (reference analog in parens): ``parser.py`` — lexer/recursive-descent
parser (Calcite, ``flink-sql-parser``); ``expressions.py`` — columnar closure
compiler (Janino codegen, ``codegen/``); ``planner.py`` — SELECT → DataStream
lowering (Blink planner ``PlannerBase.scala:155`` →
``StreamExecGroupWindowAggregate.java:103``); ``table_env.py`` —
``TableEnvironment``/``Table``/``TableResult`` entry points
(``TableEnvironmentImpl.java:179``).
"""

from flink_tpu.sql.expressions import ExprCompiler, PlanError
from flink_tpu.sql.parser import SqlParseError, parse
from flink_tpu.sql.planner import Planner, QueryPlan
from flink_tpu.sql.table_env import (CatalogTable, Table, TableEnvironment,
                                     TableResult)

__all__ = [
    "CatalogTable", "ExprCompiler", "PlanError", "Planner", "QueryPlan",
    "SqlParseError", "Table", "TableEnvironment", "TableResult", "parse",
]
