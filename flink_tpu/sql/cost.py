"""Cost-based join reordering — the Selinger-style optimizer stage.

The rewrite pipeline (``rules.py``) was rule-based only; this module adds
the reference's cost-based dimension (``flink-optimizer/src/main/java/org/
apache/flink/optimizer/Optimizer.java:67`` with ``compile:402``; Blink side
``PlannerBase.scala:82``), scoped to the decision with the highest payoff:
**inner-equi-join order**.

- **Statistics**: row counts + per-column NDV captured at registration for
  in-memory tables (``TableStats``); sources without stats keep syntactic
  order (the reference behaves the same without catalog statistics).
- **Cardinality model**: filtered base cardinalities (classic selectivity
  heuristics: equality 1/NDV, range 0.3, default 0.25, conjunct product)
  and equi-join selectivity ``1 / max(ndv_left, ndv_right)``.
- **Search**: dynamic programming over CONNECTED subsets of the join graph
  (left-deep, matching the executor's chained hash joins), minimizing the
  sum of intermediate cardinalities.  n is small (<= 8 relations) so the
  2^n DP is exact — the ``GreedyJoinOrder`` fallback of textbooks isn't
  needed.
- **EXPLAIN**: the chosen order and its estimated cost (vs the syntactic
  plan's) surface through ``EXPLAIN``'s rewrite section.

Only inner joins with single-edge equi conditions over a tree-shaped join
graph reorder; anything else (outer joins, cyclic/multi-edge conditions,
missing stats) keeps the written order.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from itertools import combinations
from typing import Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.sql.parser import Binary, Column, Expr, JoinClause, SelectStmt

#: reorder cap: 2^n DP states; beyond this keep syntactic order
MAX_RELATIONS = 8


@dataclass
class TableStats:
    """Catalog statistics (``CatalogTableStatistics`` analog)."""

    row_count: int
    ndv: Dict[str, int] = field(default_factory=dict)

    @classmethod
    def from_columns(cls, data: Dict[str, np.ndarray]) -> "TableStats":
        n = 0
        ndv: Dict[str, int] = {}
        for name, arr in data.items():
            a = np.asarray(arr)
            n = max(n, a.shape[0])
            try:
                ndv[name] = int(len(np.unique(a)))
            except TypeError:
                ndv[name] = max(a.shape[0], 1)
        return cls(row_count=n, ndv=ndv)


def _conjuncts(e: Optional[Expr]) -> List[Expr]:
    if e is None:
        return []
    from flink_tpu.sql.rules import _conjuncts as _rule_conjuncts
    return _rule_conjuncts(e)


def filter_selectivity(pred: Optional[Expr], stats: TableStats) -> float:
    """Classic System-R heuristics, conjuncts multiplied."""
    sel = 1.0
    for c in _conjuncts(pred):
        if isinstance(c, Binary) and c.op == "=":
            col = c.left if isinstance(c.left, Column) else (
                c.right if isinstance(c.right, Column) else None)
            nd = stats.ndv.get(col.name) if col is not None else None
            sel *= 1.0 / nd if nd else 0.1
        elif isinstance(c, Binary) and c.op in ("<", ">", "<=", ">="):
            sel *= 0.3
        else:
            sel *= 0.25
    return max(sel, 1e-9)


@dataclass
class _Rel:
    idx: int
    table: str
    alias: str
    pre_filter: Optional[Expr]
    rows: float                      # post-filter estimate
    ndv: Dict[str, int]


@dataclass
class _Edge:
    a: int
    b: int
    col_a: str
    col_b: str
    on: Expr

    def other(self, i: int) -> int:
        return self.b if i == self.a else self.a

    def selectivity(self, rels: List[_Rel]) -> float:
        nd = max(rels[self.a].ndv.get(self.col_a, 0),
                 rels[self.b].ndv.get(self.col_b, 0), 1)
        return 1.0 / nd


def _resolve(col: Column, rels: List[_Rel]) -> Optional[Tuple[int, str]]:
    if col.table is not None:
        for r in rels:
            if r.alias == col.table:
                return (r.idx, col.name) if col.name in r.ndv else None
        return None
    owners = [r.idx for r in rels if col.name in r.ndv]
    return (owners[0], col.name) if len(owners) == 1 else None


def _cardinality(subset: frozenset, rels: List[_Rel],
                 edges: List[_Edge]) -> float:
    card = 1.0
    for i in subset:
        card *= max(rels[i].rows, 1.0)
    for e in edges:
        if e.a in subset and e.b in subset:
            card *= e.selectivity(rels)
    return card


def _order_cost(order: List[int], rels: List[_Rel],
                edges: List[_Edge]) -> float:
    """Sum of intermediate (and final) join output cardinalities —
    the left-deep pipeline's materialization cost."""
    cost = 0.0
    s: set = {order[0]}
    for t in order[1:]:
        s.add(t)
        cost += _cardinality(frozenset(s), rels, edges)
    return cost


def _best_order(rels: List[_Rel],
                edges: List[_Edge]) -> Tuple[List[int], float]:
    """Exact DP over connected subsets; left-deep orders."""
    n = len(rels)
    neighbors: Dict[int, set] = {i: set() for i in range(n)}
    for e in edges:
        neighbors[e.a].add(e.b)
        neighbors[e.b].add(e.a)
    best: Dict[frozenset, Tuple[float, List[int]]] = {
        frozenset([i]): (0.0, [i]) for i in range(n)}
    for size in range(2, n + 1):
        for subset in combinations(range(n), size):
            s = frozenset(subset)
            card_s = None
            entry = None
            for t in subset:
                rest = s - {t}
                prev = best.get(rest)
                if prev is None or not (neighbors[t] & rest):
                    continue
                if card_s is None:
                    card_s = _cardinality(s, rels, edges)
                cost = prev[0] + card_s
                if entry is None or cost < entry[0]:
                    entry = (cost, prev[1] + [t])
            if entry is not None:
                best[s] = entry
    full = best.get(frozenset(range(n)))
    if full is None:                       # disconnected join graph
        return list(range(n)), float("inf")
    return full[1], full[0]


def join_reorder(stmt: SelectStmt, catalog) -> Optional[SelectStmt]:
    """Rewrite rule: pick the cheapest left-deep inner-join order by the
    cost model above.  Returns None (no change) when inapplicable."""
    if not isinstance(stmt, SelectStmt):
        return None                        # UNION branches rewrite per leg
    if getattr(stmt, "join_order_cost", None) is not None:
        return None                        # already decided this query
    joins = stmt.joins
    if len(joins) < 2 or len(joins) + 1 > MAX_RELATIONS:
        return None
    if any(j.kind != "inner" for j in joins):
        return None                        # outer joins pin their order
    from flink_tpu.sql.parser import Star
    if any(isinstance(it.expr, Star) for it in stmt.items):
        return None    # SELECT * exposes post-join column ORDER — the
        #                schema must not depend on the optimizer's choice
    # relations with stats
    rels: List[_Rel] = []
    names = [(stmt.table, stmt.table_alias, stmt.scan_filter)] + [
        (j.table, j.alias, j.pre_filter) for j in joins]
    for i, (tbl, alias, pre) in enumerate(names):
        if not isinstance(tbl, str):
            return None                    # derived-table base: keep order
        ct = catalog.get(tbl) if hasattr(catalog, "get") else None
        get_stats = getattr(ct, "get_stats", None) if ct is not None else None
        stats = get_stats() if get_stats is not None else None
        if stats is None:
            return None                    # no stats: keep syntactic order
        rows = stats.row_count * filter_selectivity(pre, stats)
        rels.append(_Rel(i, tbl, alias or tbl, pre, rows, stats.ndv))
    # edges from the ON conditions (single equi edge each)
    edges: List[_Edge] = []
    for j in joins:
        on = j.on
        if not (isinstance(on, Binary) and on.op == "="
                and isinstance(on.left, Column)
                and isinstance(on.right, Column)):
            return None
        a = _resolve(on.left, rels)
        b = _resolve(on.right, rels)
        if a is None or b is None or a[0] == b[0]:
            return None
        edges.append(_Edge(a[0], b[0], a[1], b[1], on))
    # tree check: n edges over n+1 nodes must be acyclic/connected for the
    # one-edge-per-join rebuild below to hold
    seen: set = set()
    parent = list(range(len(rels)))

    def find(x):
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    for e in edges:
        ra, rb = find(e.a), find(e.b)
        if ra == rb:
            return None                    # cyclic condition graph
        parent[ra] = rb
    seen = {find(i) for i in range(len(rels))}
    if len(seen) != 1:
        return None                        # disconnected (cross join)

    order, cost = _best_order(rels, edges)
    syntactic = list(range(len(rels)))
    syn_cost = _order_cost(syntactic, rels, edges)
    note = (f"order={[rels[i].alias for i in order]} est_cost={cost:.0f} "
            f"(syntactic={syn_cost:.0f})")
    if order == syntactic:
        return replace(stmt, join_order_cost=note)
    # rebuild: new base + joins, each carrying the edge that connects it
    by_edge: Dict[int, List[_Edge]] = {}
    for e in edges:
        by_edge.setdefault(e.a, []).append(e)
        by_edge.setdefault(e.b, []).append(e)
    placed = {order[0]}
    new_joins: List[JoinClause] = []
    for t in order[1:]:
        connecting = [e for e in by_edge.get(t, ())
                      if e.other(t) in placed]
        if len(connecting) != 1:           # tree property guarantees 1
            return None
        r = rels[t]
        new_joins.append(JoinClause(
            table=r.table,
            alias=names[t][1],
            kind="inner", on=connecting[0].on, pre_filter=r.pre_filter))
        placed.add(t)
    base = rels[order[0]]
    return replace(stmt, table=base.table, table_alias=names[order[0]][1],
                   scan_filter=base.pre_filter, joins=new_joins,
                   join_order_cost=note)
