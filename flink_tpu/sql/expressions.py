"""Vectorized SQL expression compiler.

The reference code-generates Java source for every expression and aggregate
handler and compiles it with Janino at plan time
(``flink-table-planner-blink/.../codegen/``, ``ExprCodeGenerator`` et al.) —
"make the inner loop native".  The TPU-native analog compiles each expression
tree into a **columnar closure** ``fn(cols) -> array`` built from numpy/jax
ops: the whole batch is evaluated in one vectorized call, and numeric
closures are jax-traceable so XLA fuses them into the surrounding device step
(the operator-chaining/codegen fusion of ``OperatorCodeGenerator.scala``).
"""

from __future__ import annotations

import re
from typing import Any, Callable, Dict, Mapping, Optional

import numpy as np

from flink_tpu.sql.parser import (Between, Binary, Call, Case, Cast, Column,
                                  Expr, InList, Interval, IsNull, Like,
                                  Literal, SqlParseError, Star, Unary)

ColumnFn = Callable[[Mapping[str, Any]], Any]


class PlanError(ValueError):
    pass


def _is_int(a) -> bool:
    return getattr(np.asarray(a), "dtype", np.dtype(object)).kind in "iu"


def _sql_div(a, b):
    """SQL/Java integer division truncates toward zero; float division
    otherwise (Calcite semantics)."""
    if _is_int(a) and _is_int(b):
        a = np.asarray(a)
        b = np.asarray(b)
        q = np.floor_divide(a, b)
        # floor_divide rounds toward -inf; Java truncates toward zero, so
        # bump by one when operand signs differ and division was inexact
        return np.where((q * b != a) & ((a < 0) != (b < 0)), q + 1, q)
    return np.asarray(a, np.float64) / np.asarray(b, np.float64)


def _sql_mod(a, b):
    """SQL/Java remainder: sign follows the dividend (np.mod follows the
    divisor — MOD(-7, 2) must be -1, not 1)."""
    r = np.fmod(a, b)
    if _is_int(a) and _is_int(b):
        return r.astype(np.result_type(a, b))
    return r


def _as_str(a) -> np.ndarray:
    arr = np.asarray(a)
    if arr.dtype.kind in "OU":
        return arr.astype(str)
    return arr.astype(str)


_TYPE_CASTS = {
    "TINYINT": np.int8, "SMALLINT": np.int16, "INT": np.int32,
    "INTEGER": np.int32, "BIGINT": np.int64, "FLOAT": np.float32,
    "REAL": np.float32, "DOUBLE": np.float64, "DECIMAL": np.float64,
    "NUMERIC": np.float64, "BOOLEAN": bool, "TIMESTAMP": np.int64,
    "DATE": np.int64,
}


def _cast(value, type_name: str):
    ty = type_name.upper()
    if ty in ("VARCHAR", "CHAR", "STRING"):
        return _as_str(value).astype(object)
    np_ty = _TYPE_CASTS.get(ty)
    if np_ty is None:
        raise PlanError(f"unsupported CAST target {type_name!r}")
    arr = np.asarray(value)
    if arr.dtype.kind in "OU":
        if np_ty is bool:
            # SQL string→boolean by literal value, not Python truthiness
            lowered = np.char.lower(arr.astype(str))
            truth = np.isin(lowered, ("true", "t", "1", "yes"))
            bad = ~truth & ~np.isin(lowered, ("false", "f", "0", "no", ""))
            if bad.any():
                raise PlanError(
                    f"cannot CAST {arr[bad][0]!r} to BOOLEAN")
            return truth
        arr = arr.astype(str).astype(np.float64)
    return arr.astype(np_ty)


# scalar function registry: NAME -> impl(*arg_arrays) -> array
SCALAR_FUNCS: Dict[str, Callable[..., Any]] = {
    "ABS": lambda x: np.abs(x),
    "CEIL": lambda x: np.ceil(x),
    "CEILING": lambda x: np.ceil(x),
    "FLOOR": lambda x: np.floor(x),
    "ROUND": lambda x, d=None: np.round(x, int(d) if d is not None else 0),
    "SQRT": lambda x: np.sqrt(np.asarray(x, np.float64)),
    "EXP": lambda x: np.exp(np.asarray(x, np.float64)),
    "LN": lambda x: np.log(np.asarray(x, np.float64)),
    "LOG10": lambda x: np.log10(np.asarray(x, np.float64)),
    "POWER": lambda x, y: np.power(np.asarray(x, np.float64), y),
    # fmod = truncated modulo (sign of dividend), matching Java/Calcite %
    "MOD": lambda x, y: _sql_mod(x, y),
    "SIGN": lambda x: np.sign(x),
    "UPPER": lambda s: np.char.upper(_as_str(s)).astype(object),
    "LOWER": lambda s: np.char.lower(_as_str(s)).astype(object),
    "TRIM": lambda s: np.char.strip(_as_str(s)).astype(object),
    "LTRIM": lambda s: np.char.lstrip(_as_str(s)).astype(object),
    "RTRIM": lambda s: np.char.rstrip(_as_str(s)).astype(object),
    "CHAR_LENGTH": lambda s: np.char.str_len(_as_str(s)).astype(np.int32),
    "CHARACTER_LENGTH": lambda s: np.char.str_len(_as_str(s)).astype(np.int32),
    "LENGTH": lambda s: np.char.str_len(_as_str(s)).astype(np.int32),
    "CONCAT": lambda *ss: _concat(*ss),
    "COALESCE": lambda *xs: xs[0],  # engine has no NULLs; first arg wins
    "LEAST": lambda *xs: np.minimum.reduce([np.asarray(x) for x in xs]),
    "GREATEST": lambda *xs: np.maximum.reduce([np.asarray(x) for x in xs]),
    "IF": lambda c, a, b: np.where(np.asarray(c, bool), a, b),
}


def _concat(*ss):
    out = _as_str(ss[0])
    for s in ss[1:]:
        out = np.char.add(out, _as_str(s))
    return out.astype(object)


def _substring(s, start, length=None):
    strs = _as_str(s)
    start = np.asarray(start) - 1  # SQL is 1-based
    if length is None:
        return np.asarray(
            [x[int(st):] for x, st in np.broadcast(strs, start)], object)
    length = np.asarray(length)
    return np.asarray(
        [x[int(st):int(st) + int(ln)]
         for x, st, ln in np.broadcast(strs, start, length)], object)


SCALAR_FUNCS["SUBSTRING"] = _substring
SCALAR_FUNCS["SUBSTR"] = _substring


def _like_to_re(pattern: str) -> "re.Pattern":
    out = []
    for ch in pattern:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return re.compile("^" + "".join(out) + "$", re.DOTALL)


class ExprCompiler:
    """Compiles parser AST into columnar closures.

    ``resolver`` maps a column name to a closure producing its array; the
    default reads ``cols[name]`` and raises on unknown names at plan time if
    a schema is supplied.
    """

    def __init__(self, schema: Optional[Mapping[str, Any]] = None,
                 resolver: Optional[Callable[[str], Optional[ColumnFn]]] = None):
        self.schema = schema
        self.resolver = resolver

    def compile(self, expr: Expr) -> ColumnFn:
        if isinstance(expr, Literal):
            v = expr.value
            if v is None:
                raise PlanError("NULL literals are not supported (no-NULL engine)")
            return lambda cols: v
        if isinstance(expr, Interval):
            ms = expr.ms
            return lambda cols: ms
        if isinstance(expr, Column):
            if self.resolver is not None:
                fn = self.resolver(expr.name)
                if fn is not None:
                    return fn
            name = expr.name
            if self.schema is not None and name not in self.schema:
                raise PlanError(f"unknown column {name!r}; have "
                                f"{sorted(self.schema)}")
            return lambda cols: cols[name]
        if isinstance(expr, Unary):
            f = self.compile(expr.operand)
            if expr.op == "-":
                return lambda cols: -np.asarray(f(cols))
            if expr.op == "NOT":
                return lambda cols: ~np.asarray(f(cols), bool)
            raise PlanError(f"unknown unary {expr.op}")
        if isinstance(expr, Binary):
            return self._compile_binary(expr)
        if isinstance(expr, Between):
            f = self.compile(expr.expr)
            lo = self.compile(expr.lo)
            hi = self.compile(expr.hi)

            def between(cols):
                v = f(cols)
                m = (v >= lo(cols)) & (v <= hi(cols))
                return ~m if expr.negated else m
            return between
        if isinstance(expr, InList):
            f = self.compile(expr.expr)
            items = [self.compile(i) for i in expr.items]

            def in_list(cols):
                v = np.asarray(f(cols))
                m = np.zeros(v.shape, bool)
                for it in items:
                    m |= np.asarray(v == it(cols))
                return ~m if expr.negated else m
            return in_list
        if isinstance(expr, Like):
            f = self.compile(expr.expr)
            rx = _like_to_re(expr.pattern)

            def like(cols):
                vals = _as_str(f(cols))
                m = np.fromiter((rx.match(x) is not None for x in vals),
                                bool, count=len(vals))
                return ~m if expr.negated else m
            return like
        if isinstance(expr, IsNull):
            f = self.compile(expr.expr)
            negated = expr.negated

            def is_null(cols):
                v = np.asarray(f(cols))
                m = np.zeros(np.shape(v) or (1,), bool)
                return ~m if negated else m
            return is_null
        if isinstance(expr, Cast):
            f = self.compile(expr.expr)
            ty = expr.type_name
            return lambda cols: _cast(f(cols), ty)
        if isinstance(expr, Case):
            whens = [(self.compile(c), self.compile(r)) for c, r in expr.whens]
            default = self.compile(expr.default) if expr.default is not None else None

            def case(cols):
                conds = [np.asarray(c(cols), bool) for c, _ in whens]
                n = max((c.shape[0] for c in conds if c.ndim), default=1)
                if default is None:
                    # SQL default ELSE NULL; no-NULL engine zero-fills
                    first = np.asarray(whens[0][1](cols))
                    out = np.zeros(n, first.dtype if first.dtype.kind != "O" else object)
                else:
                    out = np.broadcast_to(np.asarray(default(cols)), (n,)).copy()
                # apply in reverse so the FIRST matching WHEN wins
                for cond, res in reversed(list(zip(conds, (r for _, r in whens)))):
                    out = np.where(cond, res(cols), out)
                return out
            return case
        if isinstance(expr, Call):
            return self._compile_call(expr)
        if isinstance(expr, Star):
            raise PlanError("* only valid directly in SELECT list")
        raise PlanError(f"cannot compile {expr!r}")

    def _compile_binary(self, expr: Binary) -> ColumnFn:
        lf = self.compile(expr.left)
        rf = self.compile(expr.right)
        op = expr.op
        if op == "AND":
            return lambda cols: np.asarray(lf(cols), bool) & np.asarray(rf(cols), bool)
        if op == "OR":
            return lambda cols: np.asarray(lf(cols), bool) | np.asarray(rf(cols), bool)
        if op == "||":
            return lambda cols: _concat(lf(cols), rf(cols))
        if op == "+":
            return lambda cols: np.add(lf(cols), rf(cols))
        if op == "-":
            return lambda cols: np.subtract(lf(cols), rf(cols))
        if op == "*":
            return lambda cols: np.multiply(lf(cols), rf(cols))
        if op == "/":
            return lambda cols: _sql_div(lf(cols), rf(cols))
        if op == "%":
            return lambda cols: _sql_mod(lf(cols), rf(cols))
        cmp = {"=": np.equal, "<>": np.not_equal, "<": np.less,
               "<=": np.less_equal, ">": np.greater, ">=": np.greater_equal}
        if op in cmp:
            f = cmp[op]
            return lambda cols: np.asarray(f(lf(cols), rf(cols)), bool)
        raise PlanError(f"unknown operator {op}")

    def _compile_call(self, expr: Call) -> ColumnFn:
        name = expr.name
        impl = SCALAR_FUNCS.get(name)
        if impl is None:
            raise PlanError(f"unknown function {name!r} (aggregates must be "
                            "split out by the planner before compiling)")
        arg_fns = [self.compile(a) for a in expr.args]
        return lambda cols: impl(*(f(cols) for f in arg_fns))


def expr_name(expr: Expr, i: int) -> str:
    """Derived output column name for an unaliased select item."""
    if isinstance(expr, Column):
        return expr.name
    if isinstance(expr, Call) and len(expr.args) == 1 and \
            isinstance(expr.args[0], Column):
        return f"{expr.name}_{expr.args[0].name}".lower()
    return f"EXPR${i}"


def to_column(value, n: int):
    """Broadcast a scalar compile result to a full column of length n."""
    arr = np.asarray(value)
    if arr.ndim == 0:
        if arr.dtype.kind in "OU":
            return np.full(n, arr.item(), object)
        return np.full(n, arr.item(), arr.dtype)
    return arr
