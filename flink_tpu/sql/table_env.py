"""TableEnvironment / Table / TableResult — the SQL entry points.

Analog of ``TableEnvironmentImpl.java:179`` (``executeSql:748``) and the
``Table`` API (``flink-table-api-java``): register tables over sources or
DataStreams, plan SQL through ``Planner`` onto the streaming runtime, collect
bounded results.  Each ``execute`` plans onto a FRESH
``StreamExecutionEnvironment`` so queries are isolated jobs (one job per
submission, like the reference's per-statement pipeline translation).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import numpy as np

from flink_tpu.sql.parser import SelectStmt, parse
from flink_tpu.sql.planner import Planner, PlanError, QueryPlan


@dataclass
class CatalogTable:
    """A registered table: stream factory + schema + time attributes."""

    name: str
    columns: List[str]
    stream_factory: Callable[[Any], Any]   # env -> DataStream
    rowtime: Optional[str] = None
    watermark_delay_ms: int = 0
    timestamps_assigned: bool = False
    #: False = unbounded stream (a Kafka topic, a socket): joins over it
    #: must use incremental streaming operators, never wait-for-end-of-input
    bounded: bool = True
    #: True = rows are a CHANGELOG (``op`` column carries +I/-U/+U/-D):
    #: consumers must fold retractions, and aggregates/ORDER BY over the raw
    #: rows are rejected (a -U row is not data)
    changelog: bool = False
    #: lookup (dimension) table: ``lookup(key) -> list[dict]`` probes an
    #: external system; usable only via ``JOIN t FOR SYSTEM_TIME AS OF``
    lookup: Any = None
    lookup_cache_ttl_ms: int = 60_000
    #: the dimension's key column — the join condition must equal-match it
    lookup_key: Optional[str] = None
    _bound_env: Any = None
    #: lazy catalog statistics (row count + NDV) feeding the cost-based
    #: join reorder (sql/cost.py); computed on FIRST use — registration
    #: must not pay per-column np.unique for tables never joined.
    #: None factory = unknown stats, keeps syntactic plans
    stats_factory: Any = None
    stats: Any = None

    def get_stats(self):
        if self.stats is None and self.stats_factory is not None:
            self.stats = self.stats_factory()
        return self.stats

    def stream(self):
        return self.stream_factory(self._bound_env)


class TableEnvironment:
    """Catalog + SQL planner over the streaming runtime."""

    def __init__(self, parallelism: int = 1, max_parallelism: int = 128,
                 mini_batch_rows: int = 0,
                 catalog_dir: Optional[str] = None,
                 hash_composite_keys: bool = True,
                 cep_vectorized: str = "auto"):
        self.parallelism = parallelism
        self.max_parallelism = max_parallelism
        #: >0 enables mini-batch bundling before group aggregates
        #: (``table.exec.mini-batch`` analog)
        self.mini_batch_rows = mini_batch_rows
        #: composite GROUP BY / merge keys ride the int64 hash-combine
        #: fast path (collision-checked side table) instead of per-row
        #: Python tuples; disable for multi-process SQL deployments where
        #: the pre-project and key-split maps land in different workers
        self.hash_composite_keys = hash_composite_keys
        #: MATCH_RECOGNIZE CepOperator engine mode (auto|on|off)
        self.cep_vectorized = cep_vectorized
        self._catalog: Dict[str, CatalogTable] = {}
        #: sink tables for INSERT INTO: name -> _SinkSpec
        self._sinks: Dict[str, "_SinkSpec"] = {}
        #: DDL-declared schemas, for DESCRIBE: name -> [(col, type), ...]
        self._ddl_types: Dict[str, List[Tuple[str, str]]] = {}
        #: names registered as VIEWs (DROP must match the object kind)
        self._views: set = set()
        #: durable catalog (``GenericInMemoryCatalog`` → persisted analog):
        #: every successful DDL appends to <dir>/catalog.json and replays on
        #: construction, so a catalog survives process restarts.  Point it
        #: at an object-store-backed mount for cluster-shared durability.
        self.catalog_dir = catalog_dir
        if catalog_dir:
            self._replay_catalog()

    @staticmethod
    def create(**kw) -> "TableEnvironment":
        return TableEnvironment(**kw)

    # ------------------------------------------------------- durable catalog
    def _catalog_file(self) -> str:
        import os
        return os.path.join(self.catalog_dir, "catalog.json")

    def _replay_catalog(self) -> None:
        import json
        import os
        os.makedirs(self.catalog_dir, exist_ok=True)
        path = self._catalog_file()
        if not os.path.exists(path):
            return
        with open(path) as f:
            for ddl in json.load(f):
                self._execute_ddl(ddl, persist=False)

    def _persist_ddl(self, sql: str) -> None:
        if not self.catalog_dir:
            return
        import json
        import os
        path = self._catalog_file()
        entries = []
        if os.path.exists(path):
            with open(path) as f:
                entries = json.load(f)
        entries.append(sql)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(entries, f, indent=1)
        os.replace(tmp, path)

    # ---------------------------------------------------------- registration
    def register_collection(self, name: str,
                            rows: Optional[Sequence[Mapping[str, Any]]] = None,
                            columns: Optional[Mapping[str, Any]] = None,
                            rowtime: Optional[str] = None,
                            watermark_delay_ms: int = 0,
                            batch_size: int = 4096,
                            bounded: bool = True) -> "Table":
        """Register an in-memory table (``fromValues`` analog).
        ``bounded=False`` declares it a stand-in for an unbounded stream:
        queries over it plan with incremental streaming operators (e.g. the
        changelog-emitting streaming join) instead of end-of-input ones."""
        if columns is not None:
            col_names = list(columns)
            data = {k: np.asarray(v) for k, v in columns.items()}
        elif rows:
            col_names = list(rows[0].keys())
            data = {k: np.asarray([r[k] for r in rows]) for k in col_names}
        else:
            raise ValueError("rows or columns required")

        def factory(env, _data=data, _bs=batch_size):
            return env.from_collection(columns=_data, batch_size=_bs,
                                       name=f"table:{name}")

        def make_stats(_data=data):
            from flink_tpu.sql.cost import TableStats
            return TableStats.from_columns(_data)

        ct = CatalogTable(name, col_names, factory, rowtime=rowtime,
                          watermark_delay_ms=watermark_delay_ms,
                          stats_factory=make_stats, bounded=bounded)
        self._catalog[name] = ct
        return Table(self, SelectStmt(items=[], table=name), ct)

    def register_source(self, name: str, source, columns: List[str],
                        rowtime: Optional[str] = None,
                        watermark_delay_ms: int = 0,
                        bounded: bool = True) -> "Table":
        """Register any connector ``Source`` as a table."""
        def factory(env, _src=source):
            return env.from_source(_src, name=f"table:{name}")

        ct = CatalogTable(name, list(columns), factory, rowtime=rowtime,
                          watermark_delay_ms=watermark_delay_ms,
                          bounded=bounded)
        self._catalog[name] = ct
        return Table(self, SelectStmt(items=[], table=name), ct)

    def register_lookup_table(self, name: str, lookup_fn,
                              columns: List[str],
                              key_column: Optional[str] = None,
                              cache_ttl_ms: int = 60_000) -> None:
        """Register a DIMENSION table backed by an external point-lookup
        (``lookup_fn(key) -> list[dict]``, e.g. a Postgres primary-key
        query).  Only joinable via ``JOIN name FOR SYSTEM_TIME AS OF
        o.proctime`` — the ``StreamExecLookupJoin`` shape; results are
        cached per key for ``cache_ttl_ms``."""
        def no_scan(env):
            raise PlanError(f"lookup table {name!r} cannot be scanned; use "
                            f"JOIN {name} FOR SYSTEM_TIME AS OF ...")

        self._catalog[name] = CatalogTable(
            name, list(columns), no_scan, lookup=lookup_fn,
            lookup_cache_ttl_ms=cache_ttl_ms, lookup_key=key_column)

    def create_temporary_view(self, name: str, table: "Table") -> None:
        """Register a planned query as a view (``createTemporaryView``)."""
        stmt = table._stmt

        def factory(env, _stmt=stmt):
            plan = Planner(env, self._catalog,
                           hash_composite_keys=self.hash_composite_keys,
                           cep_vectorized=self.cep_vectorized).plan(_stmt)
            return plan.stream

        cols, changelog, unbounded = self._view_traits(stmt)
        # timestamps_assigned stays False: a windowed query OVER the view
        # names its own time column, and re-assigning watermarks from it is
        # always safe on bounded inputs (the view's own event-time handling,
        # if any, already happened inside its plan)
        self._catalog[name] = CatalogTable(name, cols, factory,
                                           bounded=not unbounded,
                                           changelog=changelog)
        self._views.add(name)

    def _view_traits(self, stmt: SelectStmt):
        """Dry-plan on a throwaway env to learn a view's output schema and
        whether its rows are an (unbounded) changelog — unboundedness must
        survive the view boundary or joins over it plan end-of-input."""
        from flink_tpu.datastream.api import StreamExecutionEnvironment
        env = StreamExecutionEnvironment(parallelism=self.parallelism,
                                         max_parallelism=self.max_parallelism)
        for t in self._catalog.values():
            t._bound_env = env
        planner = Planner(env, self._catalog,
                          hash_composite_keys=self.hash_composite_keys,
                          cep_vectorized=self.cep_vectorized)
        try:
            cols = planner.plan(stmt).output_columns
            return cols, planner._changelog_join, planner._unbounded_plan
        finally:
            for t in self._catalog.values():
                t._bound_env = None

    def _output_columns(self, stmt: SelectStmt) -> List[str]:
        return self._view_traits(stmt)[0]

    # ---------------------------------------------------------------- query
    def register_sink_table(self, name: str, path: str,
                            fmt: Optional[str] = None) -> None:
        """Register a file-backed sink table — the `INSERT INTO` target
        (``CREATE TABLE ... WITH ('connector'='filesystem')`` analog).
        ``fmt`` defaults to the path's extension (csv/jsonl/ftb/avro)."""
        from flink_tpu.formats import writer_for
        resolved = fmt or path.rsplit(".", 1)[-1]
        writer_for(resolved)   # validate NOW — fail at registration, not
        #                        after the INSERT's query already ran
        self._sinks[name] = _FileSinkSpec(path, resolved)

    def sql_query(self, sql: str) -> "Table":
        return Table(self, parse(sql))

    def execute_sql(self, sql: str) -> "TableResult":
        """SELECT / UNION chains, ``INSERT INTO sink SELECT ...``,
        ``EXPLAIN <query>``, and DDL — CREATE TABLE ... WITH (connector
        properties), CREATE VIEW, DROP, SHOW TABLES, DESCRIBE
        (``TableEnvironmentImpl.executeSql:748`` dispatching DDL like
        ``TableEnvironmentImpl.java:197-205``)."""
        stripped = sql.strip()
        up = stripped.upper()
        first = up.split(None, 1)[0] if up else ""
        if first in ("CREATE", "DROP", "SHOW", "DESCRIBE", "DESC"):
            return self._execute_ddl(stripped)
        if first == "EXPLAIN":
            return _ExplainResult(self.explain_sql(stripped[len("EXPLAIN"):]))
        if first == "INSERT":
            return self._execute_insert(stripped)
        return self.sql_query(sql).execute()

    # ------------------------------------------------------------------ DDL
    def _execute_ddl(self, sql: str, persist: bool = True):
        from flink_tpu.sql.parser import (CreateTableStmt, CreateViewStmt,
                                          DescribeStmt, DropStmt,
                                          ShowTablesStmt, parse_any)
        stmt = parse_any(sql)
        if isinstance(stmt, CreateTableStmt):
            if stmt.name in self._catalog or stmt.name in self._sinks:
                if stmt.if_not_exists:
                    return _DdlResult("OK")
                raise PlanError(f"table {stmt.name!r} already exists")
            self._register_connector_table(stmt)
            if persist:
                self._persist_ddl(sql)
            return _DdlResult("OK")
        if isinstance(stmt, CreateViewStmt):
            if stmt.name in self._catalog:
                if stmt.if_not_exists:
                    return _DdlResult("OK")
                raise PlanError(f"view {stmt.name!r} already exists")
            query = stmt.query

            def factory(env, _q=query):
                return Planner(env, self._catalog).plan(_q).stream

            cols, changelog, unbounded = self._view_traits(query)
            self._catalog[stmt.name] = CatalogTable(
                stmt.name, cols, factory, bounded=not unbounded,
                changelog=changelog)
            self._views.add(stmt.name)
            if persist:
                self._persist_ddl(sql)
            return _DdlResult("OK")
        if isinstance(stmt, DropStmt):
            known = stmt.name in self._catalog or stmt.name in self._sinks
            if not known and not stmt.if_exists:
                raise PlanError(f"{stmt.kind.lower()} {stmt.name!r} does "
                                f"not exist")
            if known:
                is_view = stmt.name in self._views
                if stmt.kind == "VIEW" and not is_view:
                    raise PlanError(f"{stmt.name!r} is a table, not a "
                                    f"view (use DROP TABLE)")
                if stmt.kind == "TABLE" and is_view:
                    raise PlanError(f"{stmt.name!r} is a view, not a "
                                    f"table (use DROP VIEW)")
            self._catalog.pop(stmt.name, None)
            self._sinks.pop(stmt.name, None)
            self._ddl_types.pop(stmt.name, None)
            self._views.discard(stmt.name)
            if persist and known:
                self._persist_ddl(sql)
            return _DdlResult("OK")
        if isinstance(stmt, ShowTablesStmt):
            names = sorted(set(self._catalog) | set(self._sinks))
            return _RowsResult([{"table name": n} for n in names],
                               ["table name"])
        if isinstance(stmt, DescribeStmt):
            if stmt.name in self._ddl_types:
                rows = [{"name": c, "type": t}
                        for c, t in self._ddl_types[stmt.name]]
            elif stmt.name in self._catalog:
                rows = [{"name": c, "type": "ANY"}
                        for c in self._catalog[stmt.name].columns]
            else:
                raise PlanError(f"table {stmt.name!r} does not exist")
            return _RowsResult(rows, ["name", "type"])
        raise PlanError(f"unsupported DDL {type(stmt).__name__}")

    def _register_connector_table(self, stmt) -> None:
        """CREATE TABLE → connector registration (source and, where the
        connector writes, the INSERT INTO sink)."""
        props = stmt.properties
        conn = props.get("connector")
        if conn is None:
            raise PlanError("CREATE TABLE requires a 'connector' property")
        cols = [c.name for c in stmt.columns]
        name = stmt.name
        rowtime = stmt.watermark_column
        delay = stmt.watermark_delay_ms
        self._ddl_types[name] = [(c.name, c.type_name) for c in stmt.columns]

        if conn == "filesystem":
            path = props.get("path")
            if not path:
                raise PlanError("filesystem connector requires 'path'")
            fmt = props.get("format") or path.rsplit(".", 1)[-1]
            from flink_tpu.formats import writer_for
            writer_for(fmt)                      # validate format name

            def factory(env, _p=path, _f=fmt, _rt=rowtime):
                from flink_tpu.connectors.file_source import FileSource
                return env.from_source(
                    FileSource(_p, _f, timestamp_column=_rt),
                    name=f"table:{name}")

            self._catalog[name] = CatalogTable(
                name, cols, factory, rowtime=rowtime,
                watermark_delay_ms=delay)
            self._sinks[name] = _FileSinkSpec(path, fmt)
            return
        if conn == "kafka":
            topic = props.get("topic")
            if not topic:
                raise PlanError("kafka connector requires 'topic'")
            bootstrap = props.get("properties.bootstrap.servers",
                                  "127.0.0.1:9092")
            host, _, port_s = bootstrap.partition(":")
            port = int(port_s or 9092)
            unbounded = props.get("scan.unbounded", "false") == "true"
            fmt = props.get("format", "json")
            decoder = None
            is_cdc = fmt in ("debezium-json", "canal-json", "maxwell-json")
            if is_cdc:
                from flink_tpu.formats.cdc import cdc_decoder
                decoder = cdc_decoder(fmt)
            elif fmt != "json":
                raise PlanError(f"kafka format {fmt!r} not supported "
                                f"(json, debezium-json, canal-json, "
                                f"maxwell-json)")

            def factory(env, _h=host, _p=port, _t=topic, _rt=rowtime,
                        _dec=decoder):
                from flink_tpu.connectors.kafka import KafkaWireSource
                return env.from_source(
                    KafkaWireSource(_h, _p, _t, timestamp_column=_rt,
                                    value_decoder=_dec),
                    name=f"table:{name}")

            # a CDC table IS a changelog: its rows carry the op column and
            # downstream operators must fold retractions
            self._catalog[name] = CatalogTable(
                name, (["op"] + cols) if is_cdc else cols, factory,
                rowtime=rowtime, watermark_delay_ms=delay,
                bounded=not unbounded, changelog=is_cdc)
            if not is_cdc:
                self._sinks[name] = _KafkaSinkSpec(
                    host, port, topic,
                    key_column=props.get("sink.key-column"),
                    num_partitions=int(props.get("sink.partitions", "1")))
            return
        if conn in ("postgres", "jdbc"):
            table = props.get("table-name", name)
            host = props.get("hostname", "127.0.0.1")
            port = int(props.get("port", "5432"))
            user = props.get("username", "flink")
            password = props.get("password", "")
            part_col = props.get("scan.partition.column",
                                 stmt.primary_key or cols[0])

            def factory(env, _h=host, _p=port, _t=table, _pc=part_col,
                        _u=user, _pw=password, _c=cols):
                from flink_tpu.connectors.postgres import PostgresSource
                return env.from_source(
                    PostgresSource(_h, _p, _t, partition_column=_pc,
                                   columns=_c, user=_u, password=_pw),
                    name=f"table:{name}")

            self._catalog[name] = CatalogTable(
                name, cols, factory, rowtime=rowtime,
                watermark_delay_ms=delay)
            self._sinks[name] = _PostgresSinkSpec(host, port, table, cols,
                                                  user, password)
            return
        raise PlanError(f"unknown connector {conn!r} (have: filesystem, "
                        f"kafka, postgres)")

    def explain_sql(self, sql: str) -> str:
        """Textual physical plan: the vertex/edge list of the stream graph
        the query lowers to (``explainSql`` analog)."""
        env, plan, planner = self._plan(parse(sql), return_planner=True)
        plan.stream.collect()   # graph building needs a sink-reachable DAG
        g = env.get_stream_graph("explain")
        ep = g.to_plan()
        lines = []
        if planner.applied_rules:
            seen = dict.fromkeys(planner.applied_rules)  # ordered dedup
            lines.append("== Logical Rewrites Applied ==")
            lines.extend(f"  {r}" for r in seen)
        note = getattr(planner, "cost_note", None)
        if note is not None:
            lines.append("== Join Order (cost-based) ==")
            lines.append(f"  {note}")
        lines.append("== Physical Execution Plan ==")
        for v in ep.vertices:
            chain = " -> ".join(getattr(n, "name", "?") for n in v.chain) \
                or v.name
            lines.append(f"Vertex {v.id}: {v.name} (parallelism "
                         f"{v.parallelism}) [{chain}]")
            for e in v.out_edges:
                tgt = ep.by_id[e.target_id]
                lines.append(f"  -> {tgt.name} [{e.partitioning}]")
        lines.append(f"Output columns: {plan.output_columns}")
        return "\n".join(lines)

    def _execute_insert(self, sql: str) -> "_InsertResult":
        import re as _re

        m = _re.match(r"(?is)^INSERT\s+INTO\s+([A-Za-z_][A-Za-z_0-9]*)\s+"
                      r"(SELECT.*)$", sql)
        if not m:
            raise PlanError("INSERT syntax: INSERT INTO <sink_table> "
                            "SELECT ...")
        sink_name, query = m.group(1), m.group(2)
        if sink_name not in self._sinks:
            raise PlanError(f"unknown sink table {sink_name!r}; register it "
                            f"with register_sink_table(name, path) or "
                            f"CREATE TABLE ... WITH (...)")
        spec = self._sinks[sink_name]
        result = self.sql_query(query).execute()
        rows = result.collect()
        from flink_tpu.core.batch import RecordBatch
        batch = RecordBatch.from_rows(rows) if rows else RecordBatch({})
        n, target = spec.write([batch])
        return _InsertResult(n, target)

    def _plan(self, stmt: SelectStmt, return_planner: bool = False):
        from flink_tpu.datastream.api import StreamExecutionEnvironment
        env = StreamExecutionEnvironment(parallelism=self.parallelism,
                                         max_parallelism=self.max_parallelism)
        for t in self._catalog.values():
            t._bound_env = env
        planner = Planner(env, self._catalog,
                          mini_batch_rows=self.mini_batch_rows,
                          hash_composite_keys=self.hash_composite_keys,
                          cep_vectorized=self.cep_vectorized)
        try:
            plan = planner.plan(stmt)
        finally:
            for t in self._catalog.values():
                t._bound_env = None
        plan.changelog = planner._changelog_join
        if return_planner:
            return env, plan, planner
        return env, plan


class Table:
    """A (lazily planned) relational query (``Table`` analog)."""

    def __init__(self, tenv: TableEnvironment, stmt: SelectStmt,
                 catalog_entry: Optional[CatalogTable] = None):
        self.tenv = tenv
        self._stmt = stmt
        self._entry = catalog_entry

    # -- fluent Table API (sugar over the SQL AST) --------------------------
    def _table_name(self) -> str:
        from flink_tpu.sql.parser import UnionStmt
        if isinstance(self._stmt, UnionStmt):
            raise PlanError("fluent Table transformations are not supported "
                            "on UNION queries; use execute_sql")
        if self._stmt.table is None:
            raise PlanError("table has no FROM target")
        return self._stmt.table

    def select(self, select_list: str) -> "Table":
        """Replace the projection, keeping WHERE/GROUP BY/... intact."""
        import copy
        items = parse(f"SELECT {select_list} FROM {self._table_name()}").items
        stmt = copy.copy(self._stmt)
        stmt.items = items
        return Table(self.tenv, stmt)

    def where(self, condition: str) -> "Table":
        """AND the condition into the existing WHERE clause."""
        import copy
        from flink_tpu.sql.parser import Binary
        cond = parse(
            f"SELECT * FROM {self._table_name()} WHERE {condition}").where
        stmt = copy.copy(self._stmt)
        stmt.where = (cond if stmt.where is None
                      else Binary("AND", stmt.where, cond))
        return Table(self.tenv, stmt)

    filter = where

    def group_by(self, keys: str) -> "GroupedTable":
        return GroupedTable(self, keys)

    # -- execution ----------------------------------------------------------
    def execute(self) -> "TableResult":
        import copy
        stmt = self._stmt
        if getattr(stmt, "items", None) is not None and not stmt.items:
            # bare registered table: SELECT *
            stmt = copy.copy(stmt)
            stmt.items = parse(f"SELECT * FROM {stmt.table}").items
        env, plan = self.tenv._plan(stmt)
        return TableResult(env, plan)

    def to_data_stream(self, env=None):
        """Plan onto ``env`` (or the table env's fresh one) and return the
        result ``DataStream`` (``toDataStream`` / ``toChangelogStream``)."""
        import copy
        stmt = self._stmt
        if getattr(stmt, "items", None) is not None and not stmt.items:
            stmt = copy.copy(stmt)
            stmt.items = parse(f"SELECT * FROM {stmt.table}").items
        if env is None:
            env, plan = self.tenv._plan(stmt)
            return plan.stream
        for t in self.tenv._catalog.values():
            t._bound_env = env
        try:
            return Planner(env, self.tenv._catalog).plan(stmt).stream
        finally:
            for t in self.tenv._catalog.values():
                t._bound_env = None


    # -- blink-runtime extensions ------------------------------------------
    def _planned(self):
        import copy
        stmt = self._stmt
        if getattr(stmt, "items", None) is not None and not stmt.items:
            # bare table: fill in SELECT * but KEEP where()/group-by state
            stmt = copy.copy(stmt)
            stmt.items = parse(f"SELECT * FROM {stmt.table}").items
        return self.tenv._plan(stmt)

    @staticmethod
    def _keyed_then(stream, key_column: Optional[str], name: str, factory):
        """Route to the stateful operator by key (or send EVERYTHING to one
        subtask when unpartitioned) — per-key state is only correct when
        every row of a key meets the same operator instance."""
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.graph.transformations import Partitioning

        if key_column is not None:
            keyed = stream.key_by(key_column)
            return DataStream(keyed.env, keyed._then(name, factory,
                                                     chainable=False))
        t = stream._then(name, factory, partitioning=Partitioning.GLOBAL,
                         chainable=False)
        return DataStream(stream.env, t)

    def top_n(self, n: int, partition_by: Optional[str],
              order_by: str, ascending: bool = False) -> "TableResult":
        """Top-N per partition (``StreamExecRank`` analog): final ranked
        rows with a ``rank`` column."""
        from flink_tpu.operators.sql_ops import TopNOperator

        env, plan = self._planned()
        out = Table._keyed_then(
            plan.stream, partition_by, "sql-top-n",
            lambda: TopNOperator(n, partition_by, order_by,
                                 ascending=ascending, emit_changelog=False))
        return TableResult(env, QueryPlan(out, plan.output_columns + ["rank"]))

    def deduplicate(self, key: str, keep: str = "first",
                    order_by: Optional[str] = None) -> "TableResult":
        """Deduplication per key (``Deduplicate`` exec node analog)."""
        from flink_tpu.operators.sql_ops import DeduplicateOperator

        env, plan = self._planned()
        out = Table._keyed_then(
            plan.stream, key, "sql-deduplicate",
            lambda: DeduplicateOperator(key, keep=keep, order_column=order_by))
        return TableResult(env, QueryPlan(out, plan.output_columns))


class GroupedTable:
    def __init__(self, table: Table, keys: str):
        self.table = table
        self.keys = keys

    def select(self, select_list: str) -> Table:
        import copy
        sql = (f"SELECT {select_list} FROM {self.table._table_name()} "
               f"GROUP BY {self.keys}")
        stmt = parse(sql)
        stmt.where = copy.copy(self.table._stmt.where)  # keep prior where()
        return Table(self.table.tenv, stmt)

    def select_changelog(self, select_list: str) -> "TableResult":
        """Non-windowed group aggregate as a CHANGELOG stream with
        retraction rows (+I / -U / +U in the ``op`` column) — the
        ``GroupAggFunction`` retraction semantics of the blink runtime."""
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.operators.sql_ops import ChangelogGroupAggOperator
        from flink_tpu.sql.parser import Call, Column as PCol, Star
        from flink_tpu.sql.planner import QueryPlan as QP

        if "," in self.keys:
            raise PlanError("select_changelog supports a single group key")
        key = self.keys.strip()
        items = parse(f"SELECT {select_list} "
                      f"FROM {self.table._table_name()}").items
        agg_columns = {}
        out_cols = ["op", key]
        for it in items:
            e = it.expr
            if isinstance(e, PCol) and e.name == key:
                continue
            if not (isinstance(e, Call) and e.name in
                    ("SUM", "COUNT", "MIN", "MAX")):
                raise PlanError("select_changelog items must be the key or "
                                "SUM/COUNT/MIN/MAX aggregates")
            if e.name == "COUNT":
                col = None
            else:
                if len(e.args) != 1 or not isinstance(e.args[0], PCol):
                    raise PlanError(f"{e.name} needs one plain column arg")
                col = e.args[0].name
            out = it.alias or f"{e.name.lower()}_{col or 'rows'}"
            agg_columns[out] = (col, e.name.lower()
                                if e.name != "COUNT" else "count")
            out_cols.append(out)

        env, plan = self.table._planned()
        # a changelog input (CDC table, streaming-join view) must FOLD
        # retractions, not sum raw rows; the plan carries the trait
        # explicitly — a user column merely NAMED 'op' stays plain data
        consume = plan.changelog
        out = Table._keyed_then(
            plan.stream, key, "sql-changelog-agg",
            lambda: ChangelogGroupAggOperator(
                key, agg_columns, consume_retractions=consume))
        return TableResult(env, QP(out, out_cols))


class _SinkSpec:
    """INSERT INTO target: writes batches, returns (rows, target desc)."""

    def write(self, batches) -> Tuple[int, str]:  # pragma: no cover
        raise NotImplementedError


class _FileSinkSpec(_SinkSpec):
    def __init__(self, path: str, fmt: str):
        self.path, self.fmt = path, fmt

    def write(self, batches):
        from flink_tpu.formats import writer_for
        return writer_for(self.fmt)(batches, self.path), self.path


class _KafkaSinkSpec(_SinkSpec):
    def __init__(self, host, port, topic, key_column=None,
                 num_partitions=1):
        self.host, self.port, self.topic = host, port, topic
        self.key_column = key_column
        self.num_partitions = num_partitions

    def write(self, batches):
        from flink_tpu.connectors.kafka import KafkaWireSink
        sink = KafkaWireSink(self.host, self.port, self.topic,
                             key_column=self.key_column,
                             num_partitions=self.num_partitions)
        sink.open(None)
        n = 0
        try:
            for b in batches:
                sink.write_batch(b)
                n += len(b)
        finally:
            sink.close()
        return n, f"kafka://{self.host}:{self.port}/{self.topic}"


class _PostgresSinkSpec(_SinkSpec):
    def __init__(self, host, port, table, columns, user, password):
        self.host, self.port, self.table = host, port, table
        self.columns = columns
        self.user, self.password = user, password

    def write(self, batches):
        from flink_tpu.connectors.postgres import PostgresSink
        sink = PostgresSink(self.host, self.port, self.table,
                            columns=self.columns, user=self.user,
                            password=self.password)
        n = 0
        try:
            for b in batches:
                sink.write_batch(b)
                n += len(b)
        finally:
            sink.close()
        return n, f"postgres://{self.host}:{self.port}/{self.table}"


class _DdlResult:
    """Result of a DDL statement (``TableResultImpl.TABLE_RESULT_OK``)."""

    def __init__(self, status: str = "OK"):
        self.status = status

    def collect(self):
        return [{"result": self.status}]

    def print(self) -> None:
        print(self.status)


class _RowsResult:
    """Static rows (SHOW TABLES / DESCRIBE)."""

    def __init__(self, rows, columns):
        self._rows = rows
        self.output_columns = columns

    def collect(self):
        return self._rows

    def print(self) -> None:
        print(" | ".join(self.output_columns))
        for r in self._rows:
            print(" | ".join(str(r[c]) for c in self.output_columns))


class _ExplainResult:
    """Result of ``EXPLAIN <query>``: the plan text."""

    def __init__(self, text: str):
        self.text = text

    def collect(self):
        return [{"plan": self.text}]

    def print(self) -> None:
        print(self.text)


class _InsertResult:
    """Result of ``INSERT INTO``: rows written + target path."""

    def __init__(self, rows_written: int, path: str):
        self.rows_written = rows_written
        self.path = path

    def collect(self):
        return [{"rows_written": self.rows_written, "path": self.path}]

    def print(self) -> None:
        print(f"{self.rows_written} rows -> {self.path}")


class TableResult:
    """Bounded query result: executes the job on collect (``TableResult``)."""

    def __init__(self, env, plan: QueryPlan):
        self.env = env
        self.plan = plan
        self._rows: Optional[List[Dict[str, Any]]] = None

    @property
    def output_columns(self) -> List[str]:
        return self.plan.output_columns

    def collect(self) -> List[Dict[str, Any]]:
        if self._rows is None:
            sink = self.plan.stream.collect()
            self.env.execute("sql-query")
            rows = sink.rows()
            rows = [{k: r.get(k) for k in self.plan.output_columns}
                    for r in rows]
            if self.plan.order_by:
                keys = list(reversed(self.plan.order_by))

                def sort_key_chain(rs):
                    for name, asc in keys:
                        rs.sort(key=lambda r: r[name], reverse=not asc)
                    return rs
                rows = sort_key_chain(rows)
            if self.plan.limit is not None:
                rows = rows[: self.plan.limit]
            self._rows = rows
        return self._rows

    def print(self) -> None:
        rows = self.collect()
        cols = self.plan.output_columns
        print(" | ".join(cols))
        for r in rows:
            print(" | ".join(str(r[c]) for c in cols))
