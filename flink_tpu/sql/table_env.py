"""TableEnvironment / Table / TableResult — the SQL entry points.

Analog of ``TableEnvironmentImpl.java:179`` (``executeSql:748``) and the
``Table`` API (``flink-table-api-java``): register tables over sources or
DataStreams, plan SQL through ``Planner`` onto the streaming runtime, collect
bounded results.  Each ``execute`` plans onto a FRESH
``StreamExecutionEnvironment`` so queries are isolated jobs (one job per
submission, like the reference's per-statement pipeline translation).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence

import numpy as np

from flink_tpu.sql.parser import SelectStmt, parse
from flink_tpu.sql.planner import Planner, PlanError, QueryPlan


@dataclass
class CatalogTable:
    """A registered table: stream factory + schema + time attributes."""

    name: str
    columns: List[str]
    stream_factory: Callable[[Any], Any]   # env -> DataStream
    rowtime: Optional[str] = None
    watermark_delay_ms: int = 0
    timestamps_assigned: bool = False
    #: False = unbounded stream (a Kafka topic, a socket): joins over it
    #: must use incremental streaming operators, never wait-for-end-of-input
    bounded: bool = True
    #: True = rows are a CHANGELOG (``op`` column carries +I/-U/+U/-D):
    #: consumers must fold retractions, and aggregates/ORDER BY over the raw
    #: rows are rejected (a -U row is not data)
    changelog: bool = False
    _bound_env: Any = None
    #: lazy catalog statistics (row count + NDV) feeding the cost-based
    #: join reorder (sql/cost.py); computed on FIRST use — registration
    #: must not pay per-column np.unique for tables never joined.
    #: None factory = unknown stats, keeps syntactic plans
    stats_factory: Any = None
    stats: Any = None

    def get_stats(self):
        if self.stats is None and self.stats_factory is not None:
            self.stats = self.stats_factory()
        return self.stats

    def stream(self):
        return self.stream_factory(self._bound_env)


class TableEnvironment:
    """Catalog + SQL planner over the streaming runtime."""

    def __init__(self, parallelism: int = 1, max_parallelism: int = 128,
                 mini_batch_rows: int = 0):
        self.parallelism = parallelism
        self.max_parallelism = max_parallelism
        #: >0 enables mini-batch bundling before group aggregates
        #: (``table.exec.mini-batch`` analog)
        self.mini_batch_rows = mini_batch_rows
        self._catalog: Dict[str, CatalogTable] = {}
        #: sink tables for INSERT INTO: name -> (path, format)
        self._sinks: Dict[str, Tuple[str, str]] = {}

    @staticmethod
    def create(**kw) -> "TableEnvironment":
        return TableEnvironment(**kw)

    # ---------------------------------------------------------- registration
    def register_collection(self, name: str,
                            rows: Optional[Sequence[Mapping[str, Any]]] = None,
                            columns: Optional[Mapping[str, Any]] = None,
                            rowtime: Optional[str] = None,
                            watermark_delay_ms: int = 0,
                            batch_size: int = 4096,
                            bounded: bool = True) -> "Table":
        """Register an in-memory table (``fromValues`` analog).
        ``bounded=False`` declares it a stand-in for an unbounded stream:
        queries over it plan with incremental streaming operators (e.g. the
        changelog-emitting streaming join) instead of end-of-input ones."""
        if columns is not None:
            col_names = list(columns)
            data = {k: np.asarray(v) for k, v in columns.items()}
        elif rows:
            col_names = list(rows[0].keys())
            data = {k: np.asarray([r[k] for r in rows]) for k in col_names}
        else:
            raise ValueError("rows or columns required")

        def factory(env, _data=data, _bs=batch_size):
            return env.from_collection(columns=_data, batch_size=_bs,
                                       name=f"table:{name}")

        def make_stats(_data=data):
            from flink_tpu.sql.cost import TableStats
            return TableStats.from_columns(_data)

        ct = CatalogTable(name, col_names, factory, rowtime=rowtime,
                          watermark_delay_ms=watermark_delay_ms,
                          stats_factory=make_stats, bounded=bounded)
        self._catalog[name] = ct
        return Table(self, SelectStmt(items=[], table=name), ct)

    def register_source(self, name: str, source, columns: List[str],
                        rowtime: Optional[str] = None,
                        watermark_delay_ms: int = 0,
                        bounded: bool = True) -> "Table":
        """Register any connector ``Source`` as a table."""
        def factory(env, _src=source):
            return env.from_source(_src, name=f"table:{name}")

        ct = CatalogTable(name, list(columns), factory, rowtime=rowtime,
                          watermark_delay_ms=watermark_delay_ms,
                          bounded=bounded)
        self._catalog[name] = ct
        return Table(self, SelectStmt(items=[], table=name), ct)

    def create_temporary_view(self, name: str, table: "Table") -> None:
        """Register a planned query as a view (``createTemporaryView``)."""
        stmt = table._stmt

        def factory(env, _stmt=stmt):
            plan = Planner(env, self._catalog).plan(_stmt)
            return plan.stream

        cols, changelog, unbounded = self._view_traits(stmt)
        # timestamps_assigned stays False: a windowed query OVER the view
        # names its own time column, and re-assigning watermarks from it is
        # always safe on bounded inputs (the view's own event-time handling,
        # if any, already happened inside its plan)
        self._catalog[name] = CatalogTable(name, cols, factory,
                                           bounded=not unbounded,
                                           changelog=changelog)

    def _view_traits(self, stmt: SelectStmt):
        """Dry-plan on a throwaway env to learn a view's output schema and
        whether its rows are an (unbounded) changelog — unboundedness must
        survive the view boundary or joins over it plan end-of-input."""
        from flink_tpu.datastream.api import StreamExecutionEnvironment
        env = StreamExecutionEnvironment(parallelism=self.parallelism,
                                         max_parallelism=self.max_parallelism)
        for t in self._catalog.values():
            t._bound_env = env
        planner = Planner(env, self._catalog)
        try:
            cols = planner.plan(stmt).output_columns
            return cols, planner._changelog_join, planner._unbounded_plan
        finally:
            for t in self._catalog.values():
                t._bound_env = None

    def _output_columns(self, stmt: SelectStmt) -> List[str]:
        return self._view_traits(stmt)[0]

    # ---------------------------------------------------------------- query
    def register_sink_table(self, name: str, path: str,
                            fmt: Optional[str] = None) -> None:
        """Register a file-backed sink table — the `INSERT INTO` target
        (``CREATE TABLE ... WITH ('connector'='filesystem')`` analog).
        ``fmt`` defaults to the path's extension (csv/jsonl/ftb/avro)."""
        from flink_tpu.formats import writer_for
        resolved = fmt or path.rsplit(".", 1)[-1]
        writer_for(resolved)   # validate NOW — fail at registration, not
        #                        after the INSERT's query already ran
        self._sinks[name] = (path, resolved)

    def sql_query(self, sql: str) -> "Table":
        return Table(self, parse(sql))

    def execute_sql(self, sql: str) -> "TableResult":
        """SELECT / UNION chains, ``INSERT INTO sink SELECT ...``, and
        ``EXPLAIN <query>`` (``TableEnvironment.executeSql:748`` analog)."""
        stripped = sql.strip()
        up = stripped.upper()
        if up.startswith("EXPLAIN"):
            return _ExplainResult(self.explain_sql(stripped[len("EXPLAIN"):]))
        if up.startswith("INSERT"):
            return self._execute_insert(stripped)
        return self.sql_query(sql).execute()

    def explain_sql(self, sql: str) -> str:
        """Textual physical plan: the vertex/edge list of the stream graph
        the query lowers to (``explainSql`` analog)."""
        env, plan, planner = self._plan(parse(sql), return_planner=True)
        plan.stream.collect()   # graph building needs a sink-reachable DAG
        g = env.get_stream_graph("explain")
        ep = g.to_plan()
        lines = []
        if planner.applied_rules:
            seen = dict.fromkeys(planner.applied_rules)  # ordered dedup
            lines.append("== Logical Rewrites Applied ==")
            lines.extend(f"  {r}" for r in seen)
        note = getattr(planner, "cost_note", None)
        if note is not None:
            lines.append("== Join Order (cost-based) ==")
            lines.append(f"  {note}")
        lines.append("== Physical Execution Plan ==")
        for v in ep.vertices:
            chain = " -> ".join(getattr(n, "name", "?") for n in v.chain) \
                or v.name
            lines.append(f"Vertex {v.id}: {v.name} (parallelism "
                         f"{v.parallelism}) [{chain}]")
            for e in v.out_edges:
                tgt = ep.by_id[e.target_id]
                lines.append(f"  -> {tgt.name} [{e.partitioning}]")
        lines.append(f"Output columns: {plan.output_columns}")
        return "\n".join(lines)

    def _execute_insert(self, sql: str) -> "_InsertResult":
        import re as _re

        m = _re.match(r"(?is)^INSERT\s+INTO\s+([A-Za-z_][A-Za-z_0-9]*)\s+"
                      r"(SELECT.*)$", sql)
        if not m:
            raise PlanError("INSERT syntax: INSERT INTO <sink_table> "
                            "SELECT ...")
        sink_name, query = m.group(1), m.group(2)
        if sink_name not in self._sinks:
            raise PlanError(f"unknown sink table {sink_name!r}; register it "
                            f"with register_sink_table(name, path)")
        path, fmt = self._sinks[sink_name]
        result = self.sql_query(query).execute()
        rows = result.collect()
        from flink_tpu.core.batch import RecordBatch
        from flink_tpu.formats import writer_for
        batch = RecordBatch.from_rows(rows) if rows else RecordBatch({})
        n = writer_for(fmt)([batch], path)
        return _InsertResult(n, path)

    def _plan(self, stmt: SelectStmt, return_planner: bool = False):
        from flink_tpu.datastream.api import StreamExecutionEnvironment
        env = StreamExecutionEnvironment(parallelism=self.parallelism,
                                         max_parallelism=self.max_parallelism)
        for t in self._catalog.values():
            t._bound_env = env
        planner = Planner(env, self._catalog,
                          mini_batch_rows=self.mini_batch_rows)
        try:
            plan = planner.plan(stmt)
        finally:
            for t in self._catalog.values():
                t._bound_env = None
        if return_planner:
            return env, plan, planner
        return env, plan


class Table:
    """A (lazily planned) relational query (``Table`` analog)."""

    def __init__(self, tenv: TableEnvironment, stmt: SelectStmt,
                 catalog_entry: Optional[CatalogTable] = None):
        self.tenv = tenv
        self._stmt = stmt
        self._entry = catalog_entry

    # -- fluent Table API (sugar over the SQL AST) --------------------------
    def _table_name(self) -> str:
        from flink_tpu.sql.parser import UnionStmt
        if isinstance(self._stmt, UnionStmt):
            raise PlanError("fluent Table transformations are not supported "
                            "on UNION queries; use execute_sql")
        if self._stmt.table is None:
            raise PlanError("table has no FROM target")
        return self._stmt.table

    def select(self, select_list: str) -> "Table":
        """Replace the projection, keeping WHERE/GROUP BY/... intact."""
        import copy
        items = parse(f"SELECT {select_list} FROM {self._table_name()}").items
        stmt = copy.copy(self._stmt)
        stmt.items = items
        return Table(self.tenv, stmt)

    def where(self, condition: str) -> "Table":
        """AND the condition into the existing WHERE clause."""
        import copy
        from flink_tpu.sql.parser import Binary
        cond = parse(
            f"SELECT * FROM {self._table_name()} WHERE {condition}").where
        stmt = copy.copy(self._stmt)
        stmt.where = (cond if stmt.where is None
                      else Binary("AND", stmt.where, cond))
        return Table(self.tenv, stmt)

    filter = where

    def group_by(self, keys: str) -> "GroupedTable":
        return GroupedTable(self, keys)

    # -- execution ----------------------------------------------------------
    def execute(self) -> "TableResult":
        import copy
        stmt = self._stmt
        if getattr(stmt, "items", None) is not None and not stmt.items:
            # bare registered table: SELECT *
            stmt = copy.copy(stmt)
            stmt.items = parse(f"SELECT * FROM {stmt.table}").items
        env, plan = self.tenv._plan(stmt)
        return TableResult(env, plan)

    def to_data_stream(self, env=None):
        """Plan onto ``env`` (or the table env's fresh one) and return the
        result ``DataStream`` (``toDataStream`` / ``toChangelogStream``)."""
        import copy
        stmt = self._stmt
        if getattr(stmt, "items", None) is not None and not stmt.items:
            stmt = copy.copy(stmt)
            stmt.items = parse(f"SELECT * FROM {stmt.table}").items
        if env is None:
            env, plan = self.tenv._plan(stmt)
            return plan.stream
        for t in self.tenv._catalog.values():
            t._bound_env = env
        try:
            return Planner(env, self.tenv._catalog).plan(stmt).stream
        finally:
            for t in self.tenv._catalog.values():
                t._bound_env = None


    # -- blink-runtime extensions ------------------------------------------
    def _planned(self):
        import copy
        stmt = self._stmt
        if getattr(stmt, "items", None) is not None and not stmt.items:
            # bare table: fill in SELECT * but KEEP where()/group-by state
            stmt = copy.copy(stmt)
            stmt.items = parse(f"SELECT * FROM {stmt.table}").items
        return self.tenv._plan(stmt)

    @staticmethod
    def _keyed_then(stream, key_column: Optional[str], name: str, factory):
        """Route to the stateful operator by key (or send EVERYTHING to one
        subtask when unpartitioned) — per-key state is only correct when
        every row of a key meets the same operator instance."""
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.graph.transformations import Partitioning

        if key_column is not None:
            keyed = stream.key_by(key_column)
            return DataStream(keyed.env, keyed._then(name, factory,
                                                     chainable=False))
        t = stream._then(name, factory, partitioning=Partitioning.GLOBAL,
                         chainable=False)
        return DataStream(stream.env, t)

    def top_n(self, n: int, partition_by: Optional[str],
              order_by: str, ascending: bool = False) -> "TableResult":
        """Top-N per partition (``StreamExecRank`` analog): final ranked
        rows with a ``rank`` column."""
        from flink_tpu.operators.sql_ops import TopNOperator

        env, plan = self._planned()
        out = Table._keyed_then(
            plan.stream, partition_by, "sql-top-n",
            lambda: TopNOperator(n, partition_by, order_by,
                                 ascending=ascending, emit_changelog=False))
        return TableResult(env, QueryPlan(out, plan.output_columns + ["rank"]))

    def deduplicate(self, key: str, keep: str = "first",
                    order_by: Optional[str] = None) -> "TableResult":
        """Deduplication per key (``Deduplicate`` exec node analog)."""
        from flink_tpu.operators.sql_ops import DeduplicateOperator

        env, plan = self._planned()
        out = Table._keyed_then(
            plan.stream, key, "sql-deduplicate",
            lambda: DeduplicateOperator(key, keep=keep, order_column=order_by))
        return TableResult(env, QueryPlan(out, plan.output_columns))


class GroupedTable:
    def __init__(self, table: Table, keys: str):
        self.table = table
        self.keys = keys

    def select(self, select_list: str) -> Table:
        import copy
        sql = (f"SELECT {select_list} FROM {self.table._table_name()} "
               f"GROUP BY {self.keys}")
        stmt = parse(sql)
        stmt.where = copy.copy(self.table._stmt.where)  # keep prior where()
        return Table(self.table.tenv, stmt)

    def select_changelog(self, select_list: str) -> "TableResult":
        """Non-windowed group aggregate as a CHANGELOG stream with
        retraction rows (+I / -U / +U in the ``op`` column) — the
        ``GroupAggFunction`` retraction semantics of the blink runtime."""
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.operators.sql_ops import ChangelogGroupAggOperator
        from flink_tpu.sql.parser import Call, Column as PCol, Star
        from flink_tpu.sql.planner import QueryPlan as QP

        if "," in self.keys:
            raise PlanError("select_changelog supports a single group key")
        key = self.keys.strip()
        items = parse(f"SELECT {select_list} "
                      f"FROM {self.table._table_name()}").items
        agg_columns = {}
        out_cols = ["op", key]
        for it in items:
            e = it.expr
            if isinstance(e, PCol) and e.name == key:
                continue
            if not (isinstance(e, Call) and e.name in
                    ("SUM", "COUNT", "MIN", "MAX")):
                raise PlanError("select_changelog items must be the key or "
                                "SUM/COUNT/MIN/MAX aggregates")
            if e.name == "COUNT":
                col = None
            else:
                if len(e.args) != 1 or not isinstance(e.args[0], PCol):
                    raise PlanError(f"{e.name} needs one plain column arg")
                col = e.args[0].name
            out = it.alias or f"{e.name.lower()}_{col or 'rows'}"
            agg_columns[out] = (col, e.name.lower()
                                if e.name != "COUNT" else "count")
            out_cols.append(out)

        env, plan = self.table._planned()
        out = Table._keyed_then(
            plan.stream, key, "sql-changelog-agg",
            lambda: ChangelogGroupAggOperator(key, agg_columns))
        return TableResult(env, QP(out, out_cols))


class _ExplainResult:
    """Result of ``EXPLAIN <query>``: the plan text."""

    def __init__(self, text: str):
        self.text = text

    def collect(self):
        return [{"plan": self.text}]

    def print(self) -> None:
        print(self.text)


class _InsertResult:
    """Result of ``INSERT INTO``: rows written + target path."""

    def __init__(self, rows_written: int, path: str):
        self.rows_written = rows_written
        self.path = path

    def collect(self):
        return [{"rows_written": self.rows_written, "path": self.path}]

    def print(self) -> None:
        print(f"{self.rows_written} rows -> {self.path}")


class TableResult:
    """Bounded query result: executes the job on collect (``TableResult``)."""

    def __init__(self, env, plan: QueryPlan):
        self.env = env
        self.plan = plan
        self._rows: Optional[List[Dict[str, Any]]] = None

    @property
    def output_columns(self) -> List[str]:
        return self.plan.output_columns

    def collect(self) -> List[Dict[str, Any]]:
        if self._rows is None:
            sink = self.plan.stream.collect()
            self.env.execute("sql-query")
            rows = sink.rows()
            rows = [{k: r.get(k) for k in self.plan.output_columns}
                    for r in rows]
            if self.plan.order_by:
                keys = list(reversed(self.plan.order_by))

                def sort_key_chain(rs):
                    for name, asc in keys:
                        rs.sort(key=lambda r: r[name], reverse=not asc)
                    return rs
                rows = sort_key_chain(rows)
            if self.plan.limit is not None:
                rows = rows[: self.plan.limit]
            self._rows = rows
        return self._rows

    def print(self) -> None:
        rows = self.collect()
        cols = self.plan.output_columns
        print(" | ".join(cols))
        for r in rows:
            print(" | ".join(str(r[c]) for c in cols))
