"""SQL planner: SELECT AST → DataStream pipeline.

The reference's Blink planner lowers Calcite plans through optimization into
``ExecNode``s that build stream operators — the group-window path being
``StreamExecGroupWindowAggregate.java:103`` → ``WindowOperatorBuilder``
(``createWindowOperator:345``) with a code-generated aggregate handler.  Here
the lowering is direct: WHERE → vectorized filter, expression evaluation →
columnar closures (``expressions.py``, the codegen analog), GROUP BY
TUMBLE/HOP/SESSION → the paned ``WindowAggOperator`` / merging
``SessionWindowOperator`` with a ``TupleAggregator`` (one accumulator pytree
holding every aggregate — the ``NamespaceAggsHandleFunction`` analog), and a
final projection map.  Bounded non-windowed GROUP BY runs on ``GlobalWindows``
firing at end-of-input.
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace
from typing import Any, Callable, Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

from flink_tpu.core.functions import (AvgAggregator, CountAggregator,
                                      MaxAggregator, MinAggregator,
                                      SumAggregator, TupleAggregator)
from flink_tpu.sql.expressions import (ExprCompiler, PlanError, expr_name,
                                       to_column)
from flink_tpu.sql.parser import (AGG_FUNCS, WINDOW_AUX, WINDOW_FUNCS, Between,
                                  Binary, Call, Case, Cast, Column, Expr,
                                  InList, Interval, IsNull, Like, Literal,
                                  OverCall, SelectItem, SelectStmt, Star,
                                  Unary)
from flink_tpu.windowing.assigners import (EventTimeSessionWindows,
                                           GlobalWindows,
                                           SlidingEventTimeWindows,
                                           TumblingEventTimeWindows)


@dataclass
class AggSpec:
    """One aggregate call split out of the select/having expressions."""

    out_name: str       # "__agg0", ... — ACC entry + fired column name
    func: str           # SUM/COUNT/AVG/MIN/MAX
    arg: Optional[Expr]  # None for COUNT(*)
    distinct: bool = False


@dataclass
class WindowSpec:
    kind: str          # TUMBLE/HOP/SESSION
    time_col: str
    size_ms: int
    slide_ms: Optional[int] = None  # HOP only
    offset_ms: int = 0              # synthetic TUMBLE alignment (HOP dedup)


@dataclass
class QueryPlan:
    """Planned query: the output DataStream + result metadata."""

    stream: Any                       # DataStream producing the result rows
    output_columns: List[str]
    order_by: List[Tuple[str, bool]] = field(default_factory=list)
    limit: Optional[int] = None
    #: time-attribute propagation (the reference's rowtime column survives
    #: projections): output column carrying the rowtime, if any, and whether
    #: batch timestamps are already assigned in-stream — consumed when the
    #: plan feeds a derived table
    rowtime: Optional[str] = None
    timestamps_assigned: bool = False
    #: the result rows are a CHANGELOG (op column carries the change kind)
    #: — set by TableEnvironment._plan from the planner's per-plan flag;
    #: consumers must fold retractions, never sniff column names
    changelog: bool = False


def _transform(expr: Expr, fn: Callable[[Expr], Optional[Expr]]) -> Expr:
    """Generic top-down rewrite over frozen AST nodes: ``fn`` returns a
    replacement (whole-subtree matches win) or None to recurse."""
    hit = fn(expr)
    if hit is not None:
        return hit
    rec = lambda e: _transform(e, fn)  # noqa: E731
    if isinstance(expr, Unary):
        return Unary(expr.op, rec(expr.operand))
    if isinstance(expr, Binary):
        return Binary(expr.op, rec(expr.left), rec(expr.right))
    if isinstance(expr, Call):
        return Call(expr.name, tuple(rec(a) for a in expr.args), expr.distinct)
    if isinstance(expr, OverCall):
        return OverCall(
            expr.func,
            rec(expr.partition_by) if expr.partition_by is not None else None,
            rec(expr.order_by) if expr.order_by is not None else None,
            expr.ascending, tuple(rec(a) for a in expr.args),
            expr.frame_rows, expr.frame_range_ms, expr.frame_is_rows,
            expr.distinct)
    if isinstance(expr, Cast):
        return Cast(rec(expr.expr), expr.type_name)
    if isinstance(expr, Case):
        return Case(tuple((rec(c), rec(r)) for c, r in expr.whens),
                    rec(expr.default) if expr.default is not None else None)
    if isinstance(expr, Between):
        return Between(rec(expr.expr), rec(expr.lo), rec(expr.hi), expr.negated)
    if isinstance(expr, InList):
        return InList(rec(expr.expr), tuple(rec(i) for i in expr.items),
                      expr.negated)
    if isinstance(expr, Like):
        return Like(rec(expr.expr), expr.pattern, expr.negated)
    if isinstance(expr, IsNull):
        return IsNull(rec(expr.expr), expr.negated)
    return expr


def _walk_replace(expr: Expr, mapping: Dict[Expr, Expr]) -> Expr:
    """Structural find/replace (GROUP BY expressions → key columns), plus
    window auxiliary calls (``TUMBLE_START(...)`` etc.,
    ``StreamExecGroupWindowAggregate`` window-property resolution) → the
    ``window_start``/``window_end`` columns the window operators emit."""
    def fn(e: Expr) -> Optional[Expr]:
        if e in mapping:
            return mapping[e]
        if isinstance(e, Call) and e.name in WINDOW_AUX:
            if e.name.endswith("_START"):
                return Column("window_start")
            if e.name.endswith("_END"):
                return Column("window_end")
            # *_ROWTIME / *_PROCTIME = window.maxTimestamp = end - 1
            return Binary("-", Column("window_end"), Literal(1))
        return None
    return _transform(expr, fn)


def _rewrite_qualified(stmt: SelectStmt, qual_map,
                       ambiguous: Optional[set] = None) -> SelectStmt:
    """Resolve ``alias.col`` references to flat post-join column names and
    strip qualifiers (single-table queries validate the alias too).
    ``ambiguous``: bare names that exist on both join sides — referencing
    one unqualified is an error, not a silent left-side pick."""
    import copy as _copy

    amb = ambiguous or set()

    def fn(e: Expr) -> Optional[Expr]:
        if isinstance(e, Column) and e.table is not None:
            key = (e.table, e.name)
            if key not in qual_map:
                known = sorted({t for t, _ in qual_map})
                raise PlanError(f"{e.table}.{e.name}: unknown qualifier "
                                f"(tables in scope: {known})")
            return Column(qual_map[key])
        if isinstance(e, Column) and e.name in amb:
            raise PlanError(f"column {e.name!r} is ambiguous after JOIN — "
                            f"qualify it with a table alias")
        return None

    stmt = _copy.copy(stmt)
    stmt.items = [SelectItem(_transform(it.expr, fn), it.alias)
                  for it in stmt.items]
    if stmt.where is not None:
        stmt.where = _transform(stmt.where, fn)
    stmt.group_by = [_transform(g, fn) for g in stmt.group_by]
    if stmt.having is not None:
        stmt.having = _transform(stmt.having, fn)
    stmt.order_by = [(_transform(e, fn), asc) for e, asc in stmt.order_by]
    return stmt


def _extract_aggs(expr: Expr, specs: List[AggSpec],
                  cache: Dict[Expr, Column]) -> Expr:
    """Replace aggregate calls with placeholder columns, collecting specs
    (full node coverage via the generic ``_transform`` walker)."""
    def fn(e: Expr) -> Optional[Expr]:
        if isinstance(e, Call) and e.name in AGG_FUNCS:
            if e in cache:
                return cache[e]
            arg = None
            if not (len(e.args) == 1 and isinstance(e.args[0], Star)):
                if len(e.args) != 1:
                    raise PlanError(f"{e.name} takes exactly one argument")
                arg = e.args[0]
            if e.distinct and arg is None:
                raise PlanError(f"{e.name}(DISTINCT *) is meaningless")
            name = f"__agg{len(specs)}"
            specs.append(AggSpec(name, e.name, arg, distinct=e.distinct))
            col = Column(name)
            cache[e] = col
            return col
        return None
    return _transform(expr, fn)


def _copy_stmt(stmt: SelectStmt) -> SelectStmt:
    import copy as _c
    out = _c.copy(stmt)
    out.items = list(stmt.items)
    out.group_by = list(stmt.group_by)
    out.order_by = list(stmt.order_by)
    out.joins = list(stmt.joins)
    return out


def _extract_overs(expr: Expr, specs: List[Tuple[str, OverCall]],
                   cache: Dict[Expr, Column]) -> Expr:
    """Replace OVER calls with placeholder columns (``__overN``), collecting
    (placeholder, OverCall) pairs — the ``StreamExecOverAggregate`` split."""
    def fn(e: Expr) -> Optional[Expr]:
        if isinstance(e, OverCall):
            if e in cache:
                return cache[e]
            name = f"__over{len(specs)}"
            specs.append((name, e))
            col = Column(name)
            cache[e] = col
            return col
        return None
    return _transform(expr, fn)


def _rank_filter_limit(where: Optional[Expr], rn: str) -> Optional[int]:
    """Match ``rn <= N`` / ``rn < N`` / ``N >= rn`` -> N (else None)."""
    if not isinstance(where, Binary):
        return None
    op, l, r = where.op, where.left, where.right
    if isinstance(l, Column) and l.name == rn and isinstance(r, Literal) \
            and isinstance(r.value, (int, float)):
        if op == "<=":
            return int(r.value)
        if op == "<":
            return int(r.value) - 1
    if isinstance(r, Column) and r.name == rn and isinstance(l, Literal) \
            and isinstance(l.value, (int, float)):
        if op == ">=":
            return int(l.value)
        if op == ">":
            return int(l.value) - 1
    return None


def _propagated_rowtime(table, items: List[SelectItem],
                        names: List[str]) -> Optional[str]:
    """Output column name carrying the table's rowtime through a projection
    (None when the projection drops or derives over it)."""
    if table.rowtime is None:
        return None
    for it, nm in zip(items, names):
        if isinstance(it.expr, Column) and it.expr.name == table.rowtime:
            return nm
    return None


class KeyHashCollisionError(RuntimeError):
    """Two distinct composite keys hashed to the same int64 — the
    hash-combine fast path cannot represent this stream; re-run with
    ``hash_composite_keys=False`` (the object-tuple path)."""


class _CompositeKeyHasher:
    """int64 hash-combine fast path for composite keys, shared by the
    GROUP BY pre-projection (``__key``) and the branch-merge key
    (``__merge``).

    The legacy path builds a Python tuple per ROW
    (``np.fromiter((tuple(row) ...), object)``) — per-record host work on
    the aggregate ingest path.  Here each numeric component column is
    mixed through splitmix64 (``state/keyindex._mix64``, the same family
    the key index probes with) with a per-position salt and folded into
    one int64 — a handful of vectorized passes per batch.

    Collisions are CHECKED, not assumed away: a host side table keeps one
    bit-signature (and, when ``keep_components`` is set, the component
    values) per distinct hash; every batch verifies its rows against the
    table (vectorized searchsorted + lane compare) and raises
    :class:`KeyHashCollisionError` on a genuine 64-bit collision.  The
    component columns double as the split-back table for
    ``sql-key-split`` — the post-aggregate map recovers ``__k<i>``
    columns from fired hashes with one sorted-array gather.

    Non-numeric components (strings, objects) are not eligible —
    ``combine`` returns ``None`` and the caller falls back to the tuple
    path."""

    def __init__(self, keep_components: bool = False):
        self.keep_components = keep_components
        self._known = np.empty(0, np.int64)       # sorted distinct hashes
        self._sigs: List[np.ndarray] = []         # per part: uint64 lanes
        self._vals: List[np.ndarray] = []         # per part: orig values
        #: LOCKED-IN representation: the first batch decides hash-vs-tuple
        #: and every later batch must agree — a key column whose dtype
        #: drifts mid-stream (a None turning int64 into object) must not
        #: silently split one logical key into two representations
        self._mode: Optional[str] = None          # "hash" | "tuple"
        import threading
        self._lock = threading.Lock()

    def __getstate__(self):
        d = self.__dict__.copy()
        d["_lock"] = None
        return d

    def __setstate__(self, d):
        import threading
        self.__dict__.update(d)
        self._lock = threading.Lock()

    @staticmethod
    def _lane(part, n) -> Optional[np.ndarray]:
        """One component column -> uint64 bit lane; None = ineligible."""
        a = np.asarray(part)
        if a.shape != (n,):
            return None
        if a.dtype.kind == "b":
            a = a.astype(np.int64)
        if a.dtype.kind in "iu":
            return np.ascontiguousarray(a.astype(np.int64)).view(np.uint64)
        if a.dtype.kind == "f":
            f = np.ascontiguousarray(a.astype(np.float64))
            f = f + 0.0             # canonicalize -0.0 (== +0.0 in SQL)
            u = f.view(np.uint64)
            # one NaN group regardless of payload bits
            return np.where(np.isnan(f),
                            np.uint64(0x7FF8000000000000), u)
        return None

    def combine(self, parts: Sequence, n: int) -> Optional[np.ndarray]:
        """Hash ``parts`` (component columns) into int64[n]; registers new
        hashes in the side table and collision-checks the batch.  Returns
        ``None`` when any component is non-numeric (caller falls back)."""
        from flink_tpu.state.keyindex import _mix64

        if self._mode == "tuple":
            return None
        lanes = []
        for i, p in enumerate(parts):
            u = self._lane(p, n)
            if u is None:
                with self._lock:
                    if self._mode == "hash":
                        raise KeyHashCollisionError(
                            f"composite key component {i} became "
                            f"non-numeric mid-stream after earlier batches "
                            f"were hashed — one representation per query; "
                            f"re-run with hash_composite_keys=False")
                    self._mode = "tuple"
                return None
            lanes.append(u)
        h = np.zeros(n, np.uint64)
        for i, u in enumerate(lanes):
            salt = np.uint64((0x9E3779B97F4A7C15 * (i + 1)) & (2**64 - 1))
            with np.errstate(over="ignore"):
                h = _mix64(h ^ _mix64(u ^ salt))
        out = h.view(np.int64).copy()
        self._check_and_register(out, lanes, parts)
        return out

    def _check_and_register(self, h: np.ndarray, lanes, parts) -> None:
        with self._lock:
            if self._mode == "tuple":
                raise KeyHashCollisionError(
                    "composite key components became numeric after earlier "
                    "batches fell back to tuples — one representation per "
                    "query; re-run with hash_composite_keys=False")
            self._mode = "hash"
        if h.size == 0:
            return
        # within-batch: rows sharing a hash must share every component lane
        # (unstable sort is fine — any occurrence's components serve as the
        # registered signature once this adjacency check passes)
        order = np.argsort(h)
        ho = h[order]
        adj = ho[1:] == ho[:-1]
        if adj.any():
            ai, bi = order[:-1][adj], order[1:][adj]
            for u in lanes:
                if (u[ai] != u[bi]).any():
                    raise KeyHashCollisionError(
                        "composite-key int64 hash collision inside a batch")
        # cross-batch: first occurrence per distinct hash vs the side table
        uniq_pos = np.concatenate([[0], np.flatnonzero(~adj) + 1])
        u_h = ho[uniq_pos]
        u_i = order[uniq_pos]
        with self._lock:
            if self._known.size:
                pos = np.searchsorted(self._known, u_h)
                safe = np.minimum(pos, self._known.size - 1)
                found = (pos < self._known.size) & (self._known[safe] == u_h)
            else:
                pos = np.zeros(u_h.size, np.int64)
                found = np.zeros(u_h.size, bool)
            for lane_idx, u in enumerate(lanes):
                if found.any() and (self._sigs[lane_idx][pos[found]]
                                    != u[u_i[found]]).any():
                    raise KeyHashCollisionError(
                        "composite-key int64 hash collision across batches")
            new = ~found
            if new.any():
                ins = pos[new]
                if not self._sigs:
                    self._sigs = [np.empty(0, np.uint64) for _ in lanes]
                    if self.keep_components:
                        self._vals = [np.empty(0, np.asarray(p).dtype)
                                      for p in parts]
                self._known = np.insert(self._known, ins, u_h[new])
                self._sigs = [np.insert(s, ins, u[u_i[new]])
                              for s, u in zip(self._sigs, lanes)]
                if self.keep_components:
                    self._vals = [np.insert(v, ins,
                                            np.asarray(p)[u_i[new]])
                                  for v, p in zip(self._vals, parts)]

    def components(self, hashes: np.ndarray) -> List[np.ndarray]:
        """Split-back: component columns for fired-row hashes (original
        dtypes, one sorted-array gather per component)."""
        h = np.asarray(hashes, np.int64)
        with self._lock:
            known, vals = self._known, list(self._vals)
        pos = np.searchsorted(known, h)
        safe = np.minimum(pos, max(known.size - 1, 0))
        if known.size == 0 or not bool((known[safe] == h).all()):
            raise KeyError(
                "composite-key hash not in this process's side table — a "
                "multi-process deployment split the pre-project and "
                "key-split maps; re-run with hash_composite_keys=False")
        return [v[safe] for v in vals]


def _dedup_by_tuple_key(stream, key_parts_fn, name: str):
    """Shared distinct lowering: add a TUPLE ``__dedup`` column (unambiguous,
    hashable for both the dedup dict and key-group routing), hash-route by it
    (at parallelism > 1 every copy of a value must meet the SAME dedup
    instance), and drop duplicates."""
    from flink_tpu.datastream.api import DataStream
    from flink_tpu.operators.sql_ops import DeduplicateOperator

    def add_key(cols, _fn=key_parts_fn):
        nrows = _n(cols)
        parts = _fn(cols, nrows)
        out = dict(cols)
        out["__dedup"] = np.fromiter(
            (tuple(row) for row in zip(*(p.tolist() for p in parts))),
            object, count=nrows)
        return out

    stream = stream.map(add_key, name=f"{name}-key")
    keyed = stream.key_by("__dedup")
    t = keyed._then(name, lambda: DeduplicateOperator("__dedup",
                                                      keep="first"),
                    chainable=False)
    return DataStream(stream.env, t)


def _contains_over_expr(expr: Expr) -> bool:
    specs: List[Tuple[str, OverCall]] = []
    _extract_overs(expr, specs, {})
    return bool(specs)


def _contains_agg(expr: Expr) -> bool:
    specs: List[AggSpec] = []
    _extract_aggs(expr, specs, {})
    return bool(specs)


def _agg_dtype():
    """Accumulator dtype for SQL aggregates.

    float64 only when jax x64 is enabled — otherwise request float32
    explicitly instead of letting jax silently truncate a float64 request
    (TPU accumulates in f32; sums are chunked per micro-batch + pane and
    tree-combined at fire time, which bounds error growth vs naive
    sequential accumulation)."""
    import jax
    import jax.numpy as jnp
    return jnp.float64 if jax.config.jax_enable_x64 else jnp.float32


def _make_aggregator(spec: AggSpec, value_col: str):
    dt = _agg_dtype()
    if spec.func == "SUM":
        return SumAggregator(dt)
    if spec.func == "AVG":
        return AvgAggregator(dt)
    if spec.func == "MIN":
        return MinAggregator(dt)
    if spec.func == "MAX":
        return MaxAggregator(dt)
    if spec.func == "COUNT":
        return CountAggregator()
    raise PlanError(f"unknown aggregate {spec.func}")


def _parse_window_call(call: Call, compiler: ExprCompiler) -> WindowSpec:
    args = call.args
    if not args or not isinstance(args[0], Column):
        raise PlanError(f"{call.name} first argument must be the rowtime column")
    time_col = args[0].name

    def interval_ms(e: Expr) -> int:
        if isinstance(e, Interval):
            return e.ms
        if isinstance(e, Literal) and isinstance(e.value, (int, float)):
            return int(e.value)
        raise PlanError(f"{call.name} expects INTERVAL arguments")

    if call.name == "TUMBLE":
        if len(args) != 2:
            raise PlanError("TUMBLE(rowtime, size_interval)")
        return WindowSpec("TUMBLE", time_col, interval_ms(args[1]))
    if call.name == "HOP":
        if len(args) != 3:
            raise PlanError("HOP(rowtime, slide_interval, size_interval)")
        return WindowSpec("HOP", time_col, interval_ms(args[2]),
                          slide_ms=interval_ms(args[1]))
    if call.name == "SESSION":
        if len(args) != 2:
            raise PlanError("SESSION(rowtime, gap_interval)")
        return WindowSpec("SESSION", time_col, interval_ms(args[1]))
    raise PlanError(f"unknown window function {call.name}")


class Planner:
    """Translates a parsed SELECT over one registered table to a DataStream."""

    def __init__(self, env, catalog: Mapping[str, "CatalogTable"],
                 mini_batch_rows: int = 0,
                 hash_composite_keys: bool = True,
                 cep_vectorized: str = "auto"):
        self.env = env
        self.catalog = catalog
        self.mini_batch_rows = mini_batch_rows
        #: int64 hash-combine fast path for composite GROUP BY / merge keys
        #: (collision-checked; _CompositeKeyHasher) — off = object tuples
        self.hash_composite_keys = hash_composite_keys
        #: threaded into the MATCH_RECOGNIZE CepOperator (auto|on|off);
        #: the operator's plan-time classifier decides the engine
        self.cep_vectorized = cep_vectorized
        #: rewrite-rule applications (rules.py), surfaced by EXPLAIN
        self.applied_rules: List[str] = []
        #: set when a join planned as an UNBOUNDED streaming join: the query
        #: output is a changelog (``op`` column) and must stay projection-only.
        #: Both flags describe the MOST RECENT plan() call (reset at entry)
        self._changelog_join = False
        #: set when the plan reads any unbounded table (join or not) —
        #: consumed at view/subquery boundaries so unboundedness propagates
        self._unbounded_plan = False

    def plan(self, stmt) -> QueryPlan:
        from flink_tpu.sql.parser import UnionStmt
        from flink_tpu.sql.rules import apply_rules

        # per-plan flags: a nested/earlier plan's changelog mode must not
        # leak into this one (UNION branches, views share the Planner)
        self._changelog_join = False
        self._unbounded_plan = False

        # ---- logical rewrite stage (PlannerBase.translate's optimize step)
        stmt = apply_rules(stmt, self.catalog, self.applied_rules)
        note = getattr(stmt, "join_order_cost", None)
        if note is not None:
            self.cost_note = note          # EXPLAIN's cost section

        if isinstance(stmt, UnionStmt):
            return self._plan_union(stmt)
        if stmt.table is None:
            raise PlanError("FROM clause is required")
        if isinstance(stmt.table, (SelectStmt, UnionStmt)):
            return self._plan_derived(stmt)
        try:
            table = self.catalog[stmt.table]
        except KeyError:
            raise PlanError(f"unknown table {stmt.table!r}; registered: "
                            f"{sorted(self.catalog)}")
        if not table.bounded:
            self._unbounded_plan = True
        if getattr(table, "changelog", False):
            # a changelog view/subquery feeds this query: same restrictions
            # and op passthrough as a direct streaming join apply
            self._changelog_join = True
        if stmt.match is not None:
            if stmt.joins:
                raise PlanError("MATCH_RECOGNIZE cannot be combined with "
                                "JOIN in one FROM clause (use a view)")
            if self._changelog_join:
                raise PlanError("MATCH_RECOGNIZE over a changelog stream "
                                "is not supported (the NFA cannot fold "
                                "-U/-D retractions); materialize the "
                                "changelog first")
            stream, table, qual_map = self._plan_match(stmt, table)
            stmt = _rewrite_qualified(stmt, qual_map)
        elif stmt.joins:
            stream, table, qual_map, ambiguous = self._plan_joins(stmt, table)
            stmt = _rewrite_qualified(stmt, qual_map, ambiguous)
        else:
            stream = table.stream()
            alias = stmt.table_alias or stmt.table
            qual_map = {(alias, c): c for c in table.columns}
            stmt = _rewrite_qualified(stmt, qual_map)
            if stmt.scan_columns is not None:
                # projection_prune rule: drop unreferenced columns at the
                # scan, before any operator carries them ("op" always
                # survives on changelogs: it is the row's change kind)
                keep = tuple(stmt.scan_columns)
                if self._changelog_join and "op" not in keep \
                        and "op" in table.columns:
                    keep = ("op",) + keep
                stream = stream.map(
                    lambda cols, _k=keep: {c: cols[c] for c in _k},
                    name=f"sql-scan-prune[{','.join(keep)}]")
                table = replace(table, columns=list(keep)) \
                    if hasattr(table, "__dataclass_fields__") else table
        schema = dict.fromkeys(table.columns)

        # ---- expand * and split aggregates out of SELECT / HAVING
        items: List[SelectItem] = []
        for it in stmt.items:
            if isinstance(it.expr, Star):
                items.extend(SelectItem(Column(c), c) for c in table.columns)
            else:
                items.append(it)

        if self._changelog_join:
            # unbounded streaming join: the result is a CHANGELOG — the op
            # column must survive projection, and row-reducing clauses have
            # no meaning over an infinite retraction stream
            if stmt.group_by or stmt.having is not None:
                raise PlanError(
                    "GROUP BY over an unbounded streaming JOIN changelog is "
                    "not supported yet; aggregate before the join or use a "
                    "windowed join")
            if stmt.order_by or stmt.limit is not None:
                raise PlanError("ORDER BY / LIMIT are not defined over an "
                                "unbounded streaming JOIN result")
            out_names_now = _output_names(items)
            if "op" not in out_names_now:
                items.insert(0, SelectItem(Column("op"), "op"))

        # ---- OVER aggregates (StreamExecOverAggregate): split out before
        # plain aggregate extraction; they append columns, not reduce rows
        over_specs: List[Tuple[str, OverCall]] = []
        over_cache: Dict[Expr, Column] = {}
        over_items = [SelectItem(_extract_overs(it.expr, over_specs,
                                                over_cache), it.alias)
                      for it in items]
        if over_specs:
            if self._changelog_join:
                raise PlanError("OVER aggregates over an unbounded streaming "
                                "JOIN changelog are not supported yet")
            return self._plan_over(stream, items, over_items, over_specs,
                                   table, stmt)

        agg_specs: List[AggSpec] = []
        agg_cache: Dict[Expr, Column] = {}
        rewritten = [SelectItem(_extract_aggs(it.expr, agg_specs, agg_cache),
                                it.alias) for it in items]
        having = (_extract_aggs(stmt.having, agg_specs, agg_cache)
                  if stmt.having is not None else None)
        if stmt.order_by and agg_cache:
            # ORDER BY SUM(x) must resolve to the same placeholder column the
            # select rewrite produced (aggregates not in SELECT are rejected
            # when the name lookup fails in _order_names)
            amap = dict(agg_cache)
            stmt.order_by = [(_transform(e, amap.get), asc)
                             for e, asc in stmt.order_by]

        # ---- classify GROUP BY entries: window call vs plain keys
        window: Optional[WindowSpec] = None
        group_keys: List[Expr] = []
        compiler = ExprCompiler(schema)
        for g in stmt.group_by:
            if isinstance(g, Call) and g.name in WINDOW_FUNCS:
                if window is not None:
                    raise PlanError("multiple window functions in GROUP BY")
                window = _parse_window_call(g, compiler)
            else:
                group_keys.append(g)

        if not agg_specs and (window or group_keys):
            raise PlanError("GROUP BY without aggregates is not supported")

        # ---- WHERE
        if stmt.where is not None:
            if _contains_agg(stmt.where):
                raise PlanError("aggregates are not allowed in WHERE")
            pred = compiler.compile(stmt.where)
            stream = stream.filter(lambda cols, _p=pred: np.asarray(
                to_column(_p(cols), _n(cols)), bool), name="sql-where")

        if self._changelog_join and agg_specs:
            raise PlanError("aggregates over an unbounded streaming JOIN "
                            "changelog are not supported yet")
        if not agg_specs:
            return self._plan_projection(stream, rewritten, table, stmt)
        return self._plan_aggregate(stream, rewritten, having, agg_specs,
                                    group_keys, window, table, stmt, compiler,
                                    orig_items=items)

    # ------------------------------------------------------------- union
    def _plan_union(self, stmt) -> QueryPlan:
        """``SELECT ... UNION [ALL] SELECT ...``: branches plan
        independently, columns align BY POSITION to the first branch's
        names, distinct unions dedup full rows (the two-input
        ``StreamExecUnion`` + dedup lowering)."""
        # mixed UNION/UNION ALL chains were restructured into nested
        # homogeneous unions by rules.union_associativity before lowering
        assert len(set(stmt.alls)) <= 1, "rewrite stage must run first"
        plans, changelog, unbounded = [], False, False
        for p in stmt.parts:
            plans.append(self.plan(p))      # plan() resets the flags...
            changelog |= self._changelog_join
            unbounded |= self._unbounded_plan
        # ...so re-assert the union of every branch's traits
        self._changelog_join = changelog
        self._unbounded_plan = unbounded
        if changelog and not all(stmt.alls):
            raise PlanError("UNION DISTINCT over a changelog stream is not "
                            "defined (deduplication would break retraction "
                            "pairing); use UNION ALL")
        base_cols = plans[0].output_columns
        streams = [plans[0].stream]
        for p in plans[1:]:
            if len(p.output_columns) != len(base_cols):
                raise PlanError(
                    f"UNION branches must have the same column count "
                    f"({len(base_cols)} vs {len(p.output_columns)})")
            s = p.stream
            if p.output_columns != base_cols:
                ren = dict(zip(p.output_columns, base_cols))

                def rename(cols, _r=ren):
                    return {_r.get(k, k): v for k, v in cols.items()}

                s = s.map(rename, name="sql-union-align")
            streams.append(s)
        out = streams[0].union(*streams[1:])

        if not all(stmt.alls):
            # UNION (distinct): drop duplicate FULL rows
            deduped = _dedup_by_tuple_key(
                out,
                lambda cols, nrows, _names=tuple(base_cols):
                [np.asarray(cols[nm]) for nm in _names],
                "sql-union-dedup")
            out = deduped.map(
                lambda cols, _names=tuple(base_cols):
                {nm: cols[nm] for nm in _names}, name="sql-union-strip")

        order_by: List[Tuple[str, bool]] = []
        for e, asc in stmt.order_by:
            if isinstance(e, Literal) and isinstance(e.value, int):
                if not 1 <= e.value <= len(base_cols):
                    raise PlanError(f"UNION ORDER BY ordinal {e.value} out "
                                    f"of range (1..{len(base_cols)})")
                order_by.append((base_cols[e.value - 1], asc))
            elif isinstance(e, Column) and e.name in base_cols:
                order_by.append((e.name, asc))
            else:
                raise PlanError("UNION ORDER BY must reference an output "
                                "column of the first branch (or an ordinal)")
        return QueryPlan(out, list(base_cols), order_by, stmt.limit)

    # --------------------------------------------------- over aggregates
    def _plan_over(self, stream, orig_items: List[SelectItem],
                   items: List[SelectItem],
                   over_specs: List[Tuple[str, OverCall]], table,
                   stmt: SelectStmt) -> QueryPlan:
        """``SELECT cols..., agg(x) OVER (PARTITION BY p ORDER BY rowtime
        [frame]) FROM t`` — rows pass through extended with frame aggregates
        (``StreamExecOverAggregate.java`` lowering; the Top-N ROW_NUMBER
        subquery shape stays on ``_try_plan_rank``)."""
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.graph.transformations import Partitioning
        from flink_tpu.operators.sql_ops import (OverAggregateOperator,
                                                 OverAggSpec)

        if stmt.group_by:
            raise PlanError("OVER aggregates cannot be combined with "
                            "GROUP BY in one SELECT (use a subquery)")
        if stmt.having is not None:
            raise PlanError("HAVING requires GROUP BY")
        for it in items:
            if _contains_agg(it.expr):
                raise PlanError("plain aggregates need GROUP BY; in an OVER "
                                "query every aggregate must have an OVER "
                                "clause")
        schema = dict.fromkeys(table.columns)
        compiler = ExprCompiler(schema)
        if stmt.where is not None:
            if _contains_agg(stmt.where) or _contains_over_expr(stmt.where):
                raise PlanError("aggregates are not allowed in WHERE")
            pred = compiler.compile(stmt.where)
            stream = stream.filter(lambda cols, _p=pred: np.asarray(
                to_column(_p(cols), _n(cols)), bool), name="sql-where")

        # ---- all OVER windows must share one partitioning + ordering
        over0 = over_specs[0][1]
        for _, oc in over_specs[1:]:
            if (oc.partition_by, oc.order_by, oc.ascending) != \
                    (over0.partition_by, over0.order_by, over0.ascending):
                raise PlanError("all OVER windows in one SELECT must share "
                                "PARTITION BY and ORDER BY")
        part_col = None
        if over0.partition_by is not None:
            if not isinstance(over0.partition_by, Column):
                raise PlanError("OVER PARTITION BY must be a plain column")
            part_col = over0.partition_by.name
        if over0.order_by is None:
            # without ORDER BY the SQL frame is the whole partition, which a
            # stream cannot produce row-by-row (the reference rejects it too)
            raise PlanError("OVER aggregates need ORDER BY <rowtime>")
        if not isinstance(over0.order_by, Column):
            raise PlanError("OVER ORDER BY must be a plain column")
        order_col = over0.order_by.name

        # ---- event-time (rowtime-ordered)
        event_time = False
        if order_col is not None:
            rowtime = table.rowtime
            if rowtime is not None and order_col != rowtime:
                raise PlanError(
                    f"OVER ORDER BY must be the table rowtime ({rowtime!r}) "
                    f"— streaming over-aggregates are time-ordered")
            if rowtime is None:
                # timestamps may already be assigned on the stream (derived
                # table), but without a known rowtime COLUMN we cannot prove
                # the ORDER BY attribute matches them — buffering by the
                # wrong attribute would silently mis-order the aggregate
                raise PlanError("OVER ORDER BY needs a time attribute with a "
                                "known rowtime column; declare a rowtime "
                                "column on the table")
            if not over0.ascending:
                raise PlanError("OVER ORDER BY on the rowtime must be ASC")
            event_time = True
            if not table.timestamps_assigned:
                stream = stream.assign_timestamps_and_watermarks(
                    table.watermark_delay_ms, timestamp_column=order_col,
                    name="sql-rowtime")

        # ---- pre-project aggregate inputs, build operator specs
        specs: List[OverAggSpec] = []
        arg_fns: List[Tuple[str, Any]] = []
        for name, oc in over_specs:
            in_col = None
            # DISTINCT over BOUNDED frames dedupes inside each frame at
            # aggregate time (the kept tail holds raw rows, so a value
            # leaving the frame re-counts correctly when another copy
            # remains); unbounded frames use first-occurrence contribution
            if oc.distinct and oc.func == "ROW_NUMBER":
                raise PlanError("ROW_NUMBER has no DISTINCT form")
            if oc.func == "ROW_NUMBER":
                if oc.args:
                    raise PlanError("ROW_NUMBER() takes no arguments")
            elif oc.func in AGG_FUNCS:
                if len(oc.args) == 1 and isinstance(oc.args[0], Star):
                    pass  # COUNT(*)
                elif len(oc.args) != 1:
                    raise PlanError(f"{oc.func} takes exactly one argument")
                else:
                    in_col = name + "_in"
                    arg_fns.append((in_col, compiler.compile(oc.args[0])))
            else:
                raise PlanError(f"{oc.func}() OVER is not supported "
                                f"(supported: {sorted(AGG_FUNCS)}, "
                                f"ROW_NUMBER)")
            specs.append(OverAggSpec(name, oc.func, in_col,
                                     rows=oc.frame_rows,
                                     range_ms=oc.frame_range_ms,
                                     is_rows=oc.frame_is_rows,
                                     distinct=oc.distinct))
        if arg_fns:
            def add_args(cols, _af=tuple(arg_fns)):
                n = _n(cols)
                out = dict(cols)
                for nm, f in _af:
                    out[nm] = to_column(f(cols), n)
                return out
            stream = stream.map(add_args, name="sql-over-args")

        factory = (lambda _s=tuple(specs), _p=part_col, _e=event_time:
                   OverAggregateOperator(list(_s), _p, event_time=_e))
        if part_col is not None:
            keyed = stream.key_by(part_col)
            t = keyed._then("sql-over-agg", factory, chainable=False)
        else:
            t = stream._then("sql-over-agg", factory,
                             partitioning=Partitioning.GLOBAL,
                             chainable=False)
        over_stream = DataStream(stream.env, t)

        # ---- final projection over (table cols + over outputs)
        post_schema = dict.fromkeys(
            list(table.columns) + [nm for nm, _ in arg_fns]
            + [name for name, _ in over_specs])
        post_compiler = ExprCompiler(post_schema)
        fns = [post_compiler.compile(it.expr) for it in items]
        names = _output_names(orig_items)

        def project(cols, _fns=fns, _names=names):
            n = _n(cols)
            return {nm: to_column(f(cols), n) for nm, f in zip(_names, _fns)}

        out = over_stream.map(project, name="sql-project")
        rowtime_out = None
        if event_time:
            for it, nm in zip(items, names):
                if isinstance(it.expr, Column) and it.expr.name == order_col:
                    rowtime_out = nm
                    break
        return QueryPlan(out, names, _order_names(stmt, items, names),
                         stmt.limit, rowtime=rowtime_out,
                         timestamps_assigned=rowtime_out is not None)

    # ------------------------------------------------------- derived tables
    def _plan_derived(self, stmt: SelectStmt) -> QueryPlan:
        """FROM (SELECT ...): plan the subquery, then the outer query over
        its output; the blink Top-N pattern (ROW_NUMBER + rn <= N filter)
        lowers to the TopN operator (``StreamExecRank``)."""
        from flink_tpu.sql.table_env import CatalogTable

        rank = self._try_plan_rank(stmt)
        if rank is not None:
            return rank
        inner = self.plan(stmt.table)
        # the nested plan() just set the flags for the SUBQUERY — capture
        # its traits before the outer plan() resets them, so unboundedness
        # and changelog-ness survive the subquery boundary
        inner_changelog = self._changelog_join
        inner_unbounded = self._unbounded_plan
        inner_stream = inner.stream
        if inner.order_by or inner.limit is not None:
            # a subquery's ORDER BY/LIMIT are part of ITS result set — apply
            # them in-stream before the outer query consumes the rows
            from flink_tpu.operators.sql_ops import SortLimitOperator
            from flink_tpu.datastream.api import DataStream
            t = inner_stream._then(
                "sql-sort-limit",
                lambda _ob=tuple(inner.order_by), _lim=inner.limit:
                SortLimitOperator(list(_ob), _lim), chainable=False)
            inner_stream = DataStream(inner_stream.env, t)
        # propagate the time attribute: the outer query may only use event
        # time if the subquery's projection carried the rowtime through
        # (the reference's rowtime-propagation rule)
        sub = CatalogTable(name="<subquery>",
                           columns=list(inner.output_columns),
                           stream_factory=lambda env: inner_stream,
                           rowtime=inner.rowtime,
                           timestamps_assigned=inner.timestamps_assigned,
                           bounded=not inner_unbounded,
                           changelog=inner_changelog)
        outer = _copy_stmt(stmt)
        outer.table = "<subquery>"
        outer.table_alias = stmt.table_alias
        saved = self.catalog
        self.catalog = dict(saved)
        self.catalog["<subquery>"] = sub
        try:
            return self.plan(outer)
        finally:
            self.catalog = saved

    def _try_plan_rank(self, stmt: SelectStmt) -> Optional[QueryPlan]:
        inner = stmt.table
        if not isinstance(inner, SelectStmt):
            return None  # a UNION subquery cannot be the Top-N shape
        over_items = [(i, it) for i, it in enumerate(inner.items)
                      if isinstance(it.expr, OverCall)]
        if not any(it.expr.func == "ROW_NUMBER" for _, it in over_items):
            # not the Top-N shape — fall through to generic derived-table
            # planning, where _plan_over handles OVER aggregates
            return None
        if len(over_items) != 1:
            raise PlanError("ROW_NUMBER Top-N allows exactly one window "
                            "function in the subquery")
        idx, over_it = over_items[0]
        over: OverCall = over_it.expr
        if over.order_by is None or not isinstance(over.order_by, Column):
            raise PlanError("ROW_NUMBER OVER needs ORDER BY <column>")
        if over.partition_by is not None and \
                not isinstance(over.partition_by, Column):
            raise PlanError("PARTITION BY must be a plain column")
        rn = over_it.alias or "rn"
        n = _rank_filter_limit(stmt.where, rn)
        if n is None:
            raise PlanError(
                f"Top-N needs an outer filter of the form {rn} <= N")
        # plan the base subquery WITHOUT the over item
        base = _copy_stmt(inner)
        base.items = [it for i, it in enumerate(inner.items) if i != idx]
        base_plan = self.plan(base)
        part_col = over.partition_by.name if over.partition_by else None
        order_col = over.order_by.name
        for c in filter(None, (part_col, order_col)):
            if c not in base_plan.output_columns:
                raise PlanError(f"rank column {c!r} must be selected in the "
                                f"subquery (have {base_plan.output_columns})")
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.graph.transformations import Partitioning
        from flink_tpu.operators.sql_ops import TopNOperator

        stream = base_plan.stream
        factory = (lambda _n=n, _p=part_col, _o=order_col,
                   _a=over.ascending: TopNOperator(
                       _n, _p, _o, ascending=_a, emit_changelog=False))
        if part_col is not None:
            keyed = stream.key_by(part_col)
            t = keyed._then("sql-rank", factory, chainable=False)
        else:
            t = stream._then("sql-rank", factory,
                            partitioning=Partitioning.GLOBAL, chainable=False)
        ranked = DataStream(stream.env, t)

        # rank column rename + outer projection over base cols + rn
        def add_rn(cols, _rn=rn):
            out = dict(cols)
            out[_rn] = out.pop("rank")
            out.pop("op", None)
            return out

        ranked = ranked.map(add_rn, name="sql-rank-name")
        out_cols = base_plan.output_columns + [rn]
        outer_items = []
        for it in stmt.items:
            if isinstance(it.expr, Star):
                outer_items.extend(SelectItem(Column(c), c) for c in out_cols)
            else:
                outer_items.append(it)
        schema = dict.fromkeys(out_cols)
        compiler = ExprCompiler(schema)
        fns = [compiler.compile(it.expr) for it in outer_items]
        names = _output_names(outer_items)

        def project(cols, _fns=fns, _names=names):
            nrows = _n(cols)
            return {nm: to_column(f(cols), nrows)
                    for nm, f in zip(_names, _fns)}

        out = ranked.map(project, name="sql-project")
        return QueryPlan(out, names, _order_names(stmt, outer_items, names),
                         stmt.limit)

    # --------------------------------------------------- MATCH_RECOGNIZE
    def _plan_match(self, stmt: SelectStmt, table):
        """Lower ``MATCH_RECOGNIZE`` onto the CEP NFA operator — the
        ``StreamExecMatch.java:90`` → ``CepOperator`` path.  PATTERN
        variables become strict-contiguity NFA stages (a row not attributed
        to any variable kills the attempt, unlike CEP's relaxed
        ``followedBy``); DEFINE conditions compile to vectorized columnar
        closures with ``PREV(col)`` resolved to a drain-time
        ``__prev_<col>`` column; MEASURES evaluate per match."""
        from flink_tpu.cep.operator import CepOperator
        from flink_tpu.cep.pattern import (AfterMatchSkipStrategy, Pattern,
                                           Stage)
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.sql.table_env import CatalogTable

        mr = stmt.match
        if len(mr.partition_by) > 1:
            raise PlanError("MATCH_RECOGNIZE supports a single PARTITION BY "
                            "column")
        for c in mr.partition_by + [mr.order_by]:
            if c not in table.columns:
                raise PlanError(f"MATCH_RECOGNIZE: unknown column {c!r}")
        if table.rowtime is not None and mr.order_by != table.rowtime:
            raise PlanError(f"MATCH_RECOGNIZE ORDER BY must be the rowtime "
                            f"column {table.rowtime!r}")
        var_names = [st.var.upper() for st in mr.pattern]
        if len(set(var_names)) != len(var_names):
            raise PlanError("duplicate PATTERN variable")
        for v in mr.defines:
            if v not in var_names:
                raise PlanError(f"DEFINE names unknown variable {v!r}")

        prev_cols: List[str] = []
        stages: List[Stage] = []
        cond_schema = dict.fromkeys(
            list(table.columns) + [f"__prev_{c}" for c in table.columns])
        for st in mr.pattern:
            cond = None
            cexpr = mr.defines.get(st.var.upper())
            if cexpr is not None:
                rewritten = self._rewrite_match_define(
                    cexpr, set(var_names), table.columns, prev_cols)
                fn = ExprCompiler(cond_schema).compile(rewritten)
                cond = (lambda cols, _f=fn: np.asarray(
                    to_column(_f(cols), _n(cols)), bool))
            stages.append(Stage(
                st.var.upper(), condition=cond, contiguity="strict",
                times_min=max(st.quant_min, 1),
                # {0,n} / {0,}: a zero lower bound means the variable may
                # match no rows at all — optional, not mandatory-once
                times_max=st.quant_max,
                optional=st.optional or st.quant_min == 0,
                # SQL quantifiers are greedy by default: a looping variable
                # takes every row it can before the next variable starts
                greedy=(st.quant_max is None
                        or st.quant_max != st.quant_min)))
        pattern = Pattern(
            stages, within_ms=mr.within_ms,
            skip_strategy=(AfterMatchSkipStrategy.SKIP_PAST_LAST_EVENT
                           if mr.after_match == "skip_past_last"
                           else AfterMatchSkipStrategy.NO_SKIP))

        part = mr.partition_by[0] if mr.partition_by else None
        measure_names, measure_exprs = [], []
        vset = set(var_names)
        for it in mr.measures:
            self._validate_measure(it.expr, vset, table.columns)
            measure_names.append(it.alias or expr_name(it.expr))
            measure_exprs.append(it.expr)
        out_cols = ([part] if part else []) + measure_names
        select_fn = _make_measure_fn(measure_names, measure_exprs,
                                     var_names, part)

        stream = table.stream()
        if not table.timestamps_assigned:
            stream = stream.assign_timestamps_and_watermarks(
                table.watermark_delay_ms, timestamp_column=mr.order_by,
                name="sql-match-rowtime")
        if part is None:
            # no PARTITION BY: one global NFA (constant key, dropped after)
            stream = stream.map(
                lambda cols: {**cols, "__match_pk": np.zeros(
                    _n(cols), np.int64)}, name="sql-match-global-key")
            key_col = "__match_pk"
        else:
            key_col = part
        keyed = stream.key_by(key_col)
        t = keyed._then(
            "sql-match-recognize",
            lambda _p=pattern, _k=key_col, _s=select_fn, _pc=list(prev_cols),
            _oc=mr.order_by, _v=self.cep_vectorized:
            CepOperator(_p, _k, _s, name="sql-match-recognize",
                        defer_conditions=True, prev_columns=_pc,
                        leftmost_order_column=_oc, vectorized=_v),
            chainable=False)
        out_stream = DataStream(keyed.env, t)
        alias = mr.alias or stmt.table_alias or stmt.table
        qual_map = {(alias, c): c for c in out_cols}
        out_table = CatalogTable(name="<match>", columns=out_cols,
                                 stream_factory=lambda env: out_stream,
                                 timestamps_assigned=True,
                                 bounded=table.bounded)
        if not table.bounded:
            self._unbounded_plan = True
        return out_stream, out_table, qual_map

    def _validate_measure(self, expr: Expr, var_names: set,
                          columns: List[str]) -> None:
        """Plan-time checks for MEASURES: every variable qualifier must be a
        PATTERN variable and every column must exist (runtime evaluation is
        per-match and would surface these lazily otherwise)."""
        def fn(e: Expr):
            if isinstance(e, Column) and e.table is not None:
                if e.table.upper() not in var_names:
                    raise PlanError(f"{e.table}.{e.name}: unknown pattern "
                                    f"variable in MEASURES")
                if e.name not in columns:
                    raise PlanError(f"MEASURES: unknown column {e.name!r}")
            return None
        _transform(expr, fn)

    def _rewrite_match_define(self, expr: Expr, var_names: set,
                              columns: List[str],
                              prev_cols: List[str]) -> Expr:
        """DEFINE condition rewrite: strip pattern-variable qualifiers
        (``DOWN.price`` = the CURRENT row's price) and resolve
        ``PREV(col)`` to the drain-time ``__prev_<col>`` column."""
        def fn(e: Expr):
            if isinstance(e, Call) and e.name == "PREV":
                if len(e.args) == 2:
                    off = e.args[1]
                    if not (isinstance(off, Literal) and off.value == 1):
                        raise PlanError("PREV with offset > 1 is not "
                                        "supported")
                elif len(e.args) != 1:
                    raise PlanError("PREV takes a column (and optional "
                                    "offset 1)")
                arg = e.args[0]
                if not isinstance(arg, Column):
                    raise PlanError("PREV argument must be a column")
                if arg.name not in columns:
                    raise PlanError(f"PREV: unknown column {arg.name!r}")
                if arg.name not in prev_cols:
                    prev_cols.append(arg.name)
                return Column(f"__prev_{arg.name}")
            if isinstance(e, Call) and e.name in ("FIRST", "LAST"):
                raise PlanError(f"{e.name} is only supported in MEASURES, "
                                f"not DEFINE")
            if isinstance(e, Column) and e.table is not None:
                if e.table.upper() not in var_names:
                    raise PlanError(f"{e.table}.{e.name}: unknown pattern "
                                    f"variable in DEFINE")
                if e.name not in columns:
                    raise PlanError(f"DEFINE: unknown column {e.name!r}")
                return Column(e.name)
            return None
        return _transform(expr, fn)

    # ------------------------------------------------------------ joins
    def _plan_joins(self, stmt: SelectStmt, base):
        """FROM a JOIN b ON ... — equi-joins chained left-deep.

        Bounded inputs lower to ``SqlJoinOperator`` (``StreamExecJoin`` over
        bounded inputs: emit at end of input).  If ANY input is unbounded,
        every join in the chain lowers to the incremental
        ``StreamingJoinOperator`` instead (``StreamExecJoin.java:61`` →
        ``StreamingJoinOperator.java:36``): both sides live in keyed state
        and the result is a changelog with an ``op`` column."""
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.graph.transformations import (Partitioning,
                                                     Transformation)
        from flink_tpu.operators.sql_ops import (SqlJoinOperator,
                                                 StreamingJoinOperator)
        from flink_tpu.sql.table_env import CatalogTable

        def _traits(t):
            return (not t.bounded) or getattr(t, "changelog", False)

        streaming = _traits(base) or any(
            _traits(self.catalog[jc.table])
            for jc in stmt.joins if jc.table in self.catalog)
        if streaming:
            self._unbounded_plan = True
        #: does the stream AT THIS POINT of the chain carry changelog rows?
        #: (regular streaming joins produce changelogs; temporal/lookup
        #: joins keep append-only rows and cannot consume changelogs)
        changelog_now = getattr(base, "changelog", False)

        # a changelog input's "op" column is the row's change kind, not
        # data: the join operator consumes it (retract on -D/-U) and must
        # not store or re-emit it as a payload column
        base_data_cols = [c for c in base.columns
                          if not (c == "op"
                                  and getattr(base, "changelog", False))]
        cur_stream = base.stream()
        if stmt.scan_filter is not None:
            # filter_pushdown rule: base-side WHERE conjuncts run pre-join
            cur_stream = self._pre_filter(cur_stream, base.columns,
                                          stmt.scan_filter,
                                          f"sql-prejoin-filter:{stmt.table}")
        a0 = stmt.table_alias or stmt.table
        qual_map: Dict[Tuple[str, str], str] = {(a0, c): c
                                                for c in base_data_cols}
        out_names: List[str] = list(base_data_cols)
        ambiguous: set = set()
        for jc in stmt.joins:
            try:
                rt = self.catalog[jc.table]
            except KeyError:
                raise PlanError(f"unknown table {jc.table!r} in JOIN")
            ralias = jc.alias or jc.table
            left_names = list(out_names)   # columns of the LEFT side only
            rt_data_cols = [c for c in rt.columns
                            if not (c == "op"
                                    and getattr(rt, "changelog", False))]
            rename: Dict[str, str] = {}
            for c in rt_data_cols:
                nm = c if c not in out_names else f"{ralias}_{c}"
                while nm in out_names:
                    nm += "_"
                if nm != c:
                    ambiguous.add(c)
                rename[c] = nm
                qual_map[(ralias, c)] = nm
                out_names.append(nm)
            lk, rk = self._resolve_equi_on(jc.on, qual_map, rt, ralias,
                                           left_names)
            if jc.system_time_of is not None:
                if changelog_now:
                    raise PlanError("temporal/lookup join over a changelog "
                                    "input is not supported (put the "
                                    "FOR SYSTEM_TIME join before the "
                                    "regular join)")
                first_join = left_names == list(base_data_cols)
                cur_stream = self._plan_system_time_join(
                    jc, rt, cur_stream, lk, rk, dict(rename),
                    list(left_names), list(rt_data_cols), qual_map,
                    base if first_join else None)
                continue
            rstream = rt.stream()
            if jc.pre_filter is not None:
                rstream = self._pre_filter(rstream, rt.columns, jc.pre_filter,
                                           f"sql-prejoin-filter:{jc.table}")
            cls = StreamingJoinOperator if streaming else SqlJoinOperator
            op_cls = (lambda _cls=cls, _lk=lk, _rk=rk, _how=jc.kind,
                      _rn=dict(rename), _lc=list(left_names),
                      _rc=list(rt_data_cols):
                      _cls(_lk, _rk, _how, _rn, left_columns=_lc,
                           right_columns=_rc))
            t = Transformation(
                name=(f"sql-streaming-join:{jc.table}" if streaming
                      else f"sql-join:{jc.table}"),
                operator_factory=op_cls,
                inputs=[cur_stream.transformation, rstream.transformation],
                input_partitionings=[Partitioning.HASH, Partitioning.HASH],
                input_key_columns=[lk, rk],
                parallelism=self.env.parallelism, chainable=False,
                max_parallelism=self.env.max_parallelism)
            cur_stream = DataStream(self.env, t)
            if streaming:
                changelog_now = True
        self._changelog_join = changelog_now
        if changelog_now:
            if "op" in out_names:
                raise PlanError("streaming JOIN inputs must not have a "
                                "column named 'op' (reserved for the "
                                "changelog kind)")
            out_names = ["op"] + out_names
        joined = CatalogTable(name="<join>", columns=out_names,
                              stream_factory=lambda env: cur_stream,
                              timestamps_assigned=False,
                              bounded=not streaming,
                              changelog=changelog_now)
        return cur_stream, joined, qual_map, ambiguous

    def _plan_system_time_join(self, jc, rt, cur_stream, lk: str, rk: str,
                               rename: Dict[str, str],
                               left_names: List[str], rt_cols: List[str],
                               qual_map, base_if_first):
        """``JOIN t FOR SYSTEM_TIME AS OF <time>`` — two shapes:

        - ``t`` registered as a LOOKUP table → ``LookupJoinOperator``
          (``StreamExecLookupJoin``): per-key external probe with TTL cache,
          observed at processing time.
        - ``t`` a regular table with a rowtime → ``TemporalJoinOperator``
          (``StreamExecTemporalJoin.java:67``): event-time versioned join,
          each left row sees the version valid at its time attribute."""
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.graph.transformations import (Partitioning,
                                                     Transformation)
        from flink_tpu.operators.sql_ops import (LookupJoinOperator,
                                                 TemporalJoinOperator)

        if jc.kind not in ("inner", "left"):
            raise PlanError("FOR SYSTEM_TIME joins support INNER and LEFT "
                            "only")
        if getattr(rt, "lookup", None) is not None:
            lk_col = getattr(rt, "lookup_key", None)
            if lk_col is not None and rk != lk_col:
                raise PlanError(f"lookup table {rt.name!r} is keyed by "
                                f"{lk_col!r}; the join must be ON "
                                f"left.col = {rt.name}.{lk_col}")
            t = Transformation(
                name=f"sql-lookup-join:{jc.table}",
                operator_factory=(
                    lambda _lk=lk, _fn=rt.lookup, _rc=list(rt_cols),
                    _rn=dict(rename), _how=jc.kind,
                    _ttl=rt.lookup_cache_ttl_ms:
                    LookupJoinOperator(_lk, _fn, _rc, _rn, _how,
                                       cache_ttl_ms=_ttl)),
                inputs=[cur_stream.transformation],
                input_partitionings=[Partitioning.HASH],
                input_key_columns=[lk],
                parallelism=self.env.parallelism, chainable=False,
                max_parallelism=self.env.max_parallelism)
            return DataStream(self.env, t)

        if rt.rowtime is None:
            raise PlanError(f"temporal join: table {jc.table!r} must "
                            f"declare a rowtime column (its version time), "
                            f"or be registered as a lookup table")
        st = jc.system_time_of
        if not isinstance(st, Column):
            raise PlanError("FOR SYSTEM_TIME AS OF must name a left-side "
                            "time column")
        if st.table is not None:
            key = (st.table, st.name)
            if key not in qual_map:
                raise PlanError(f"{st.table}.{st.name}: unknown in "
                                f"FOR SYSTEM_TIME AS OF")
            ltime = qual_map[key]
        else:
            ltime = st.name
        if ltime not in left_names:
            raise PlanError(f"FOR SYSTEM_TIME AS OF column {ltime!r} is not "
                            f"on the left side")
        if base_if_first is not None \
                and not base_if_first.timestamps_assigned:
            # drive the valve: left watermarks gate the buffered emission
            cur_stream = cur_stream.assign_timestamps_and_watermarks(
                base_if_first.watermark_delay_ms, timestamp_column=ltime,
                name="sql-temporal-left-rowtime")
        rstream = rt.stream()
        if not rt.timestamps_assigned:
            rstream = rstream.assign_timestamps_and_watermarks(
                rt.watermark_delay_ms, timestamp_column=rt.rowtime,
                name=f"sql-temporal-version-rowtime:{jc.table}")
        t = Transformation(
            name=f"sql-temporal-join:{jc.table}",
            operator_factory=(
                lambda _lk=lk, _rk=rk, _lt=ltime, _rt=rt.rowtime,
                _rc=list(rt_cols), _rn=dict(rename), _how=jc.kind:
                TemporalJoinOperator(_lk, _rk, _lt, _rt, _rc, _rn, _how)),
            inputs=[cur_stream.transformation, rstream.transformation],
            input_partitionings=[Partitioning.HASH, Partitioning.HASH],
            input_key_columns=[lk, rk],
            parallelism=self.env.parallelism, chainable=False,
            max_parallelism=self.env.max_parallelism)
        return DataStream(self.env, t)

    def _pre_filter(self, stream, columns, pred_expr: Expr, name: str):
        """Apply a pushed-down predicate (bare column names) to an input."""
        pred = ExprCompiler(dict.fromkeys(columns)).compile(pred_expr)
        return stream.filter(
            lambda cols, _p=pred: np.asarray(to_column(_p(cols), _n(cols)),
                                             bool), name=name)

    def _resolve_equi_on(self, on: Expr, qual_map, right_table, ralias: str,
                         left_names: List[str]) -> Tuple[str, str]:
        if not (isinstance(on, Binary) and on.op == "="
                and isinstance(on.left, Column)
                and isinstance(on.right, Column)):
            raise PlanError("JOIN ... ON must be an equi-join between two "
                            "columns (a.k = b.k)")

        def side(col: Column) -> Tuple[str, str]:
            """-> ('right', original right col) or ('left', output name)."""
            if col.table == ralias:
                if col.name not in right_table.columns:
                    raise PlanError(f"{ralias}.{col.name}: no such column")
                return "right", col.name
            if col.table is not None:
                key = (col.table, col.name)
                if key not in qual_map:
                    raise PlanError(f"{col.table}.{col.name}: unknown")
                return "left", qual_map[key]
            # unqualified: resolve by uniqueness across the two sides
            in_left = col.name in left_names
            in_right = col.name in right_table.columns
            if in_left and in_right:
                raise PlanError(f"column {col.name!r} is ambiguous in JOIN "
                                f"(qualify it: {ralias}.{col.name})")
            if in_right:
                return "right", col.name
            if in_left:
                return "left", col.name
            raise PlanError(f"column {col.name!r} not found in JOIN")

        s1, c1 = side(on.left)
        s2, c2 = side(on.right)
        if {s1, s2} != {"left", "right"}:
            raise PlanError("JOIN condition must relate the two tables")
        return (c1, c2) if s1 == "left" else (c2, c1)

    # ------------------------------------------------------------ projection
    def _plan_projection(self, stream, items: List[SelectItem], table,
                         stmt: SelectStmt) -> QueryPlan:
        compiler = ExprCompiler(dict.fromkeys(table.columns))
        names = _output_names(items)
        fns = [compiler.compile(it.expr) for it in items]

        def project(cols, _fns=fns, _names=names):
            n = _n(cols)
            return {nm: to_column(f(cols), n) for nm, f in zip(_names, _fns)}

        out = stream.map(project, name="sql-project")
        rowtime_out = _propagated_rowtime(table, items, names)
        return QueryPlan(out, names, _order_names(stmt, items, names),
                         stmt.limit, rowtime=rowtime_out,
                         timestamps_assigned=(rowtime_out is not None
                                              and table.timestamps_assigned))

    # ------------------------------------------------------------- aggregate
    def _plan_aggregate(self, stream, items, having, agg_specs: List[AggSpec],
                        group_keys: List[Expr], window: Optional[WindowSpec],
                        table, stmt: SelectStmt, compiler: ExprCompiler,
                        orig_items: Optional[List[SelectItem]] = None) -> QueryPlan:
        # ---- event time for windowed queries
        if window is not None:
            rowtime = table.rowtime
            if rowtime is not None and rowtime != window.time_col:
                raise PlanError(
                    f"window is over {window.time_col!r} but table rowtime is "
                    f"{rowtime!r}")
            if not table.timestamps_assigned:
                stream = stream.assign_timestamps_and_watermarks(
                    table.watermark_delay_ms, timestamp_column=window.time_col,
                    name="sql-rowtime")

        # ---- DISTINCT aggregates: dedup-then-aggregate (the classic
        # two-phase expansion of COUNT(DISTINCT x) GROUP BY k: drop duplicate
        # (k[, window], x) rows, then aggregate normally).  Mixed queries
        # split into a plain branch and a distinct branch whose fired rows
        # re-merge on (key[, window]) — the reference folds both into one
        # AggsHandleFunction with distinct-state MapViews instead.
        distinct_specs = [s for s in agg_specs if s.distinct]
        plain_specs = [s for s in agg_specs if not s.distinct]
        if distinct_specs:
            args = {repr(s.arg) for s in distinct_specs}
            if len(args) != 1:
                raise PlanError("all DISTINCT aggregates in a query must "
                                "share the same argument")

        key_exprs = group_keys
        single_col_key = (len(key_exprs) == 1 and isinstance(key_exprs[0], Column))
        key_col = key_exprs[0].name if single_col_key else "__key"
        emit_bounds = window is not None
        # ONE hasher per aggregate plan, shared by every branch's
        # pre-projection AND the post-aggregate key split — both branches
        # register into the same side table, so split_key can never consult
        # a table the other branch filled
        self._key_hasher = (_CompositeKeyHasher(keep_components=True)
                            if self.hash_composite_keys and not single_col_key
                            and len(key_exprs) > 1 else None)

        if distinct_specs and window is not None and window.kind == "SESSION":
            # merging windows have no stable identity a row-level dedup key
            # could name — instead ONE session operator carries per-session
            # distinct-value SETS that merge with the intervals
            # (SessionWindowOperator.distinct_specs, the MapView analog)
            agg_stream = self._agg_branch(stream, agg_specs, key_exprs,
                                          key_col, single_col_key, window,
                                          compiler, None,
                                          session_distinct=distinct_specs)
            return self._post_aggregate(agg_stream, items, having, agg_specs,
                                        key_exprs, single_col_key, key_col,
                                        emit_bounds, stmt, orig_items)

        if distinct_specs and plain_specs:
            a = self._agg_branch(stream, plain_specs, key_exprs, key_col,
                                 single_col_key, window, compiler, None)
            b = self._distinct_branch(stream, distinct_specs, key_exprs,
                                      key_col, single_col_key, window,
                                      compiler)
            agg_stream = self._merge_branches(
                a, b, key_col, emit_bounds,
                extra=[s.out_name for s in distinct_specs])
        elif distinct_specs:
            agg_stream = self._distinct_branch(stream, distinct_specs,
                                               key_exprs, key_col,
                                               single_col_key, window,
                                               compiler)
        else:
            agg_stream = self._agg_branch(stream, agg_specs, key_exprs,
                                          key_col, single_col_key, window,
                                          compiler, None)

        return self._post_aggregate(agg_stream, items, having, agg_specs,
                                    key_exprs, single_col_key, key_col,
                                    emit_bounds, stmt, orig_items)

    def _distinct_branch(self, stream, distinct_specs: List[AggSpec],
                         key_exprs: List[Expr], key_col: str,
                         single_col_key: bool,
                         window: Optional[WindowSpec],
                         compiler: ExprCompiler):
        """The DISTINCT pipeline.  HOP windows first EXPAND each row into
        per-covering-window copies on a synthetic per-window timestamp
        (``HopWindowExpandOperator``) so the window identity becomes part
        of the row — then the TUMBLE machinery applies unchanged; the real
        HOP bounds are recovered from the synthetic bucket afterwards."""
        from flink_tpu.datastream.api import DataStream

        if window is not None and window.kind == "HOP":
            from flink_tpu.operators.sql_ops import HopWindowExpandOperator

            size, slide = window.size_ms, window.slide_ms
            t = stream._then(
                "sql-hop-expand",
                lambda _s=size, _sl=slide: HopWindowExpandOperator(_s, _sl),
                chainable=False)
            expanded = DataStream(stream.env, t)
            # offset aligns bucket boundaries on the REAL window closes
            # (w*slide + size): every synthetic bucket ends exactly when
            # its HOP window does, so the late-drop rule matches the plain
            # branch for ANY size/slide (incl. size not a multiple of
            # slide)
            synth = WindowSpec(kind="TUMBLE", time_col="__hopts",
                               size_ms=slide, offset_ms=size % slide)
            out = self._agg_branch(expanded, distinct_specs, key_exprs,
                                   key_col, single_col_key, synth, compiler,
                                   distinct_specs[0].arg)
            shift = size - slide  # bucket [w*slide+size-slide, w*slide+size)

            def fix_bounds(cols, _shift=shift, _size=size):
                o = dict(cols)
                start = np.asarray(o["window_start"], np.int64) - _shift
                o["window_start"] = start
                o["window_end"] = start + _size
                return o

            return out.map(fix_bounds, name="sql-hop-bounds")
        return self._agg_branch(stream, distinct_specs, key_exprs, key_col,
                                single_col_key, window, compiler,
                                distinct_specs[0].arg)

    def _agg_branch(self, stream, agg_specs: List[AggSpec],
                    key_exprs: List[Expr], key_col: str,
                    single_col_key: bool, window: Optional[WindowSpec],
                    compiler: ExprCompiler, dedup_arg: Optional[Expr],
                    session_distinct: Optional[List[AggSpec]] = None):
        """One aggregate pipeline: [dedup →] pre-project → key_by → window
        aggregate, returning the fired-rows stream.  ``session_distinct``:
        DISTINCT specs handled by the session operator's per-session sets
        (excluded from the ACC pytree)."""
        from flink_tpu.datastream.api import DataStream

        if dedup_arg is not None:
            dk_fns = ([compiler.compile(k) for k in key_exprs]
                      + [compiler.compile(dedup_arg)])
            win = window

            def key_parts(cols, nrows, _fns=dk_fns, _w=win):
                parts = [to_column(f(cols), nrows) for f in _fns]
                if _w is not None:
                    # TUMBLE: the dedup scope is one window — fold the
                    # window index into the key so a value recurring in a
                    # LATER window still counts there
                    widx = ((np.asarray(cols[_w.time_col], np.int64)
                             - _w.offset_ms) // _w.size_ms)
                    parts = parts[:-1] + [widx, parts[-1]]
                return parts

            stream = _dedup_by_tuple_key(stream, key_parts,
                                         "sql-distinct-dedup")

        # ---- pre-projection: aggregate inputs + computed/composite group key
        key_fns = [compiler.compile(k) for k in key_exprs]
        arg_fns = [(s.out_name + "_in", compiler.compile(s.arg))
                   for s in agg_specs if s.arg is not None]
        need_ones = any(s.arg is None for s in agg_specs)

        hasher = getattr(self, "_key_hasher", None)

        def pre_project(cols, _kf=key_fns, _af=arg_fns,
                        _composite=not single_col_key, _ones=need_ones,
                        _h=hasher):
            n = _n(cols)
            out = dict(cols)
            for nm, f in _af:
                out[nm] = to_column(f(cols), n)
            if _ones:
                out["__ones"] = np.ones(n, np.int32)
            if _composite:
                if len(_kf) == 0:
                    out["__key"] = np.zeros(n, np.int64)  # global aggregate
                elif len(_kf) == 1:
                    out["__key"] = to_column(_kf[0](cols), n)
                else:
                    parts = [to_column(f(cols), n) for f in _kf]
                    # int64 hash-combine fast path (collision-checked) —
                    # numeric keys skip the per-row Python tuple build
                    key = _h.combine(parts, n) if _h is not None else None
                    if key is None:
                        key = np.fromiter(
                            (tuple(row)
                             for row in zip(*(p.tolist() for p in parts))),
                            object, count=n)
                    out["__key"] = key
            return out

        stream = stream.map(pre_project, name="sql-pre-project")
        if self.mini_batch_rows:
            # bundle small batches ahead of the stateful aggregate
            # (``table.exec.mini-batch`` bundling, ``operators/bundle/``)
            from flink_tpu.operators.sql_ops import MiniBatchOperator
            mbr = self.mini_batch_rows
            t = stream._then("sql-mini-batch",
                             lambda: MiniBatchOperator(mbr),
                             chainable=False)
            stream = DataStream(stream.env, t)
        keyed = stream.key_by(key_col)

        # ---- the aggregate handler: one ACC pytree for all aggregates.
        # The value selector passes ONLY numeric input columns — the update
        # step is jitted, and key/string columns must stay host-side.
        distinct_names = {s.out_name for s in (session_distinct or [])}
        agg_map: Dict[str, Tuple[str, Any]] = {}
        for s in agg_specs:
            if s.out_name in distinct_names:
                continue   # handled by the session operator's value sets
            in_col = s.out_name + "_in" if s.arg is not None else "__ones"
            agg_map[s.out_name] = (in_col, _make_aggregator(s, in_col))
        tuple_agg = TupleAggregator(agg_map)
        needed = {c for c, _ in agg_map.values()}
        if session_distinct:
            needed.add(session_distinct[0].out_name + "_in")
        needed = sorted(needed)
        select_values = lambda c, _need=tuple(needed): {k: c[k] for k in _need}  # noqa: E731

        if window is None:
            assigner = GlobalWindows()
            assigner.is_event_time = False  # fire only at end-of-input
            from flink_tpu.operators.window_agg import WindowAggOperator
            from flink_tpu.windowing.triggers import EventTimeTrigger

            def factory(_a=assigner, _agg=tuple_agg, _k=key_col):
                return WindowAggOperator(
                    _a, _agg, key_column=_k, value_selector=select_values,
                    trigger=EventTimeTrigger(), emit_window_bounds=False,
                    name="sql-group-agg")
            t = keyed._then("sql-group-agg", factory)
            return DataStream(keyed.env, t)
        if window.kind == "SESSION":
            if session_distinct:
                from flink_tpu.operators.session_window import (
                    SessionWindowOperator)
                assigner = EventTimeSessionWindows(window.size_ms)
                dspecs = {s.out_name: s.func for s in session_distinct}
                dcol = session_distinct[0].out_name + "_in"
                mesh = keyed.env.mesh

                def factory(_a=assigner, _agg=tuple_agg, _k=key_col,
                            _ds=dspecs, _dc=dcol, _m=mesh):
                    kwargs = dict(key_column=_k,
                                  value_selector=select_values,
                                  name="sql-session-agg",
                                  distinct_specs=dict(_ds),
                                  distinct_column=_dc)
                    if _m is not None:
                        from flink_tpu.parallel.mesh_runtime import (
                            MeshSessionWindowOperator)
                        return MeshSessionWindowOperator(_a, _agg, mesh=_m,
                                                         **kwargs)
                    return SessionWindowOperator(_a, _agg, **kwargs)

                t = keyed._then("sql-session-agg", factory, chainable=False)
                return DataStream(keyed.env, t)
            return keyed.window(
                EventTimeSessionWindows(window.size_ms)).aggregate(
                    tuple_agg, value_selector=select_values,
                    name="sql-session-agg")
        if window.kind == "TUMBLE":
            assigner = TumblingEventTimeWindows.of(window.size_ms,
                                                   window.offset_ms)
        else:
            assigner = SlidingEventTimeWindows.of(window.size_ms,
                                                  window.slide_ms)
        return keyed.window(assigner).aggregate(
            tuple_agg, value_selector=select_values, name="sql-window-agg")

    def _merge_branches(self, a, b, key_col: str, emit_bounds: bool,
                        extra: List[str]):
        """Re-join the fired rows of two aggregate branches on the merge key
        (group key [+ window bounds]); ``extra`` = columns only branch b
        contributes."""
        from flink_tpu.datastream.api import DataStream
        from flink_tpu.graph.transformations import (Partitioning,
                                                     Transformation)
        from flink_tpu.operators.sql_ops import BranchMergeOperator

        merge_hasher = (_CompositeKeyHasher()
                        if self.hash_composite_keys else None)

        def add_merge_key(cols, _kc=key_col, _b=emit_bounds,
                          _h=merge_hasher):
            n = _n(cols)
            out = dict(cols)
            parts = [np.asarray(cols[_kc])]
            if _b:
                parts += [np.asarray(cols["window_start"]),
                          np.asarray(cols["window_end"])]
            # same int64 hash-combine fast path as pre_project's __key —
            # BOTH branches share one hasher, so the collision check spans
            # the join (equal hashes with unequal components cannot merge)
            merge = _h.combine(parts, n) if _h is not None else None
            if merge is None:
                merge = np.fromiter(
                    (tuple(row) for row in zip(*(p.tolist() for p in parts))),
                    object, count=n)
            out["__merge"] = merge
            return out

        a = a.map(add_merge_key, name="sql-merge-key")
        b = b.map(add_merge_key, name="sql-merge-key")
        t = Transformation(
            name="sql-branch-merge",
            operator_factory=(lambda _x=tuple(extra):
                              BranchMergeOperator("__merge", list(_x))),
            inputs=[a.transformation, b.transformation],
            input_partitionings=[Partitioning.HASH, Partitioning.HASH],
            input_key_columns=["__merge", "__merge"],
            parallelism=self.env.parallelism, chainable=False,
            max_parallelism=self.env.max_parallelism)
        return DataStream(a.env, t)

    def _post_aggregate(self, agg_stream, items, having,
                        agg_specs: List[AggSpec], key_exprs: List[Expr],
                        single_col_key: bool, key_col: str,
                        emit_bounds: bool, stmt: SelectStmt,
                        orig_items: Optional[List[SelectItem]]) -> QueryPlan:
        # ---- split composite key back into its columns
        if not single_col_key and len(key_exprs) > 1:
            key_out_names = [f"__k{i}" for i in range(len(key_exprs))]
            hasher = getattr(self, "_key_hasher", None)

            def split_key(cols, _names=key_out_names, _h=hasher):
                out = dict(cols)
                tuples = np.asarray(cols["__key"])
                if _h is not None and tuples.dtype.kind in "iu":
                    # hashed fast path: recover the component columns from
                    # the shared side table (one sorted gather per part)
                    for nm, arr in zip(_names, _h.components(tuples)):
                        out[nm] = arr
                    return out
                for i, nm in enumerate(_names):
                    out[nm] = np.asarray([t[i] for t in tuples])
                return out

            agg_stream = agg_stream.map(split_key, name="sql-key-split")
            key_mapping = {k: Column(nm)
                           for k, nm in zip(key_exprs, key_out_names)}
        elif not single_col_key and len(key_exprs) == 1:
            key_mapping = {key_exprs[0]: Column("__key")}
        else:
            key_mapping = {}

        # ---- resolve select/having over the fired-batch schema
        aux_mapping: Dict[Expr, Expr] = dict(key_mapping)
        post_items = [SelectItem(_walk_replace(it.expr, aux_mapping), it.alias)
                      for it in items]
        # output names come from the user-visible items (aliases / original
        # column names like "sum_v"), not the internal __k/__agg rewrites
        names = _output_names(orig_items if orig_items is not None else items)
        # fired-batch schema: group keys + aggregate results (+ window
        # bounds) — referencing any other column is the classic "column must
        # appear in GROUP BY" SQL error, caught at plan time
        fired_schema = {s.out_name: None for s in agg_specs}
        if emit_bounds:
            fired_schema.update(window_start=None, window_end=None)
        if single_col_key:
            fired_schema[key_col] = None
        elif len(key_exprs) > 1:
            fired_schema.update({f"__k{i}": None
                                 for i in range(len(key_exprs))})
        else:
            fired_schema["__key"] = None
        post_compiler = ExprCompiler(fired_schema)

        if having is not None:
            hv = post_compiler.compile(_walk_replace(having, aux_mapping))
            agg_stream = agg_stream.filter(
                lambda cols, _p=hv: np.asarray(to_column(_p(cols), _n(cols)),
                                               bool), name="sql-having")

        fns = [post_compiler.compile(it.expr) for it in post_items]

        def project(cols, _fns=fns, _names=names):
            n = _n(cols)
            return {nm: to_column(f(cols), n) for nm, f in zip(_names, _fns)}

        out = agg_stream.map(project, name="sql-project")
        return QueryPlan(out, names, _order_names(stmt, items, names),
                         stmt.limit)


def _n(cols) -> int:
    for v in cols.values():
        return int(np.shape(v)[0])
    return 0


def _make_measure_fn(names: List[str], exprs: List[Expr],
                     var_names: List[str], part: Optional[str]):
    """MEASURES evaluator: one output row per match.  Scalar semantics of
    ``StreamExecMatch``'s generated condition/measure functions: a bare
    ``A.col`` is the LAST row mapped to A (ONE ROW PER MATCH),
    ``FIRST/LAST(A.col)`` navigate within A, aggregates fold over A's rows
    (or over the whole match when unqualified)."""
    uvars = [v.upper() for v in var_names]

    def rows_of(match, var):
        return match.get(var.upper(), [])

    def all_rows(match):
        out = []
        for v in uvars:
            out.extend(match.get(v, []))
        return out

    def last_row_value(match, name):
        for v in reversed(uvars):
            rows = match.get(v)
            if rows:
                return rows[-1].get(name)
        return None

    def agg(fn_name, vals):
        vals = [v for v in vals if v is not None]
        if fn_name == "COUNT":
            return len(vals)
        if not vals:
            return None
        if fn_name == "SUM":
            return sum(vals)
        if fn_name == "MIN":
            return min(vals)
        if fn_name == "MAX":
            return max(vals)
        if fn_name == "AVG":
            return sum(vals) / len(vals)
        raise PlanError(f"unsupported MEASURES aggregate {fn_name}")

    def ev(e: Expr, match):
        if isinstance(e, Literal):
            return e.value
        if isinstance(e, Interval):
            return e.ms
        if isinstance(e, Column):
            if e.table is not None:
                if e.table.upper() not in uvars:
                    raise PlanError(f"{e.table}.{e.name}: unknown pattern "
                                    f"variable in MEASURES")
                rows = rows_of(match, e.table)
                return rows[-1].get(e.name) if rows else None
            if part is not None and e.name == part:
                return all_rows(match)[0].get(part)
            return last_row_value(match, e.name)
        if isinstance(e, Call):
            nm = e.name
            if nm in ("FIRST", "LAST"):
                if len(e.args) != 1 or not isinstance(e.args[0], Column) \
                        or e.args[0].table is None:
                    raise PlanError(f"{nm} takes a variable-qualified "
                                    f"column (A.col)")
                rows = rows_of(match, e.args[0].table)
                if not rows:
                    return None
                row = rows[0] if nm == "FIRST" else rows[-1]
                return row.get(e.args[0].name)
            if nm in ("SUM", "COUNT", "MIN", "MAX", "AVG"):
                if len(e.args) == 1 and isinstance(e.args[0], Star):
                    return len(all_rows(match))
                if len(e.args) != 1 or not isinstance(e.args[0], Column):
                    raise PlanError(f"MEASURES {nm} takes one column")
                col = e.args[0]
                rows = (rows_of(match, col.table)
                        if col.table is not None else all_rows(match))
                return agg(nm, [r.get(col.name) for r in rows])
            raise PlanError(f"unsupported function {nm} in MEASURES")
        if isinstance(e, Unary):
            v = ev(e.operand, match)
            if e.op == "-":
                return None if v is None else -v
            return None if v is None else (not v)
        if isinstance(e, Binary):
            l, r = ev(e.left, match), ev(e.right, match)
            if e.op in ("AND", "OR"):
                return (l and r) if e.op == "AND" else (l or r)
            if l is None or r is None:
                return None
            return {"+": lambda: l + r, "-": lambda: l - r,
                    "*": lambda: l * r, "/": lambda: l / r,
                    "%": lambda: l % r, "||": lambda: str(l) + str(r),
                    "=": lambda: l == r, "<>": lambda: l != r,
                    "<": lambda: l < r, "<=": lambda: l <= r,
                    ">": lambda: l > r, ">=": lambda: l >= r}[e.op]()
        raise PlanError(f"unsupported MEASURES expression {e!r}")

    def select(match):
        row = {}
        if part is not None:
            row[part] = all_rows(match)[0].get(part)
        for nm, e in zip(names, exprs):
            row[nm] = ev(e, match)
        return row

    return select


def _output_names(items: List[SelectItem]) -> List[str]:
    names: List[str] = []
    for i, it in enumerate(items):
        nm = it.alias or expr_name(it.expr, i)
        base, k = nm, 0
        while nm in names:
            k += 1
            nm = f"{base}_{k}"
        names.append(nm)
    return names


def _order_names(stmt: SelectStmt, items: List[SelectItem],
                 names: List[str]) -> List[Tuple[str, bool]]:
    """Resolve ORDER BY entries to output column names (by alias, by matching
    select expression, or by 1-based ordinal)."""
    out: List[Tuple[str, bool]] = []
    for e, asc in stmt.order_by:
        if isinstance(e, Literal) and isinstance(e.value, int):
            out.append((names[e.value - 1], asc))
            continue
        if isinstance(e, Column):
            if e.name in names:
                out.append((e.name, asc))
                continue
        matched = None
        for it, nm in zip(items, names):
            if it.expr == e:
                matched = nm
                break
        if matched is None:
            raise PlanError(f"ORDER BY expression must appear in SELECT: {e}")
        out.append((matched, asc))
    return out
