"""feature_store scenario: high-cardinality windowed feature aggregates
published queryable and committed transactionally, read concurrently by
ROUTED BINARY clients at a paced QPS while the job runs (the PR-13
serving tier threaded into a live, autoscaling, chaos-injected job).

The committed ``features`` topic doubles as a ground-truth check: per
``(key, window_start)`` sums must equal the sums computed directly from
the generated stream — not just match the control run.
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from flink_tpu.scenarios.base import Scenario, ScenarioSpec


class FeatureStoreScenario(Scenario):
    name = "feature_store"
    budget_section = "scenario_feature_cpu"

    def spec(self, smoke: bool, records: Optional[int] = None,
             keys: Optional[int] = None) -> ScenarioSpec:
        return ScenarioSpec(
            name=self.name,
            records=records or (60_000 if smoke else 500_000),
            keys=keys or (1013 if smoke else 250_007),
            batch_size=128 if smoke else 256,
            topics=("features",),
            queryable_state="features",
            qps_target=500.0 if smoke else 2000.0,
            qps_batch_keys=128,
            seed=59, smoke=smoke)

    def build(self, env, source, sinks, spec: ScenarioSpec) -> None:
        import jax.numpy as jnp

        from flink_tpu.core.functions import SumAggregator
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        (env.from_source(source)
         .assign_timestamps_and_watermarks(0, timestamp_column="t")
         .key_by("k")
         .window(TumblingEventTimeWindows.of(spec.window_ms))
         .aggregate(SumAggregator(jnp.float64), value_column="v",
                    output_column="feature", name="feature-agg",
                    queryable="features")
         .add_sink(sinks["features"]))

    def cross_check(self, committed: Dict[str, List[dict]], source,
                    spec: ScenarioSpec) -> List[str]:
        """Absolute ground truth: committed per-(key, window) sums equal
        the sums computed directly from the generated stream.  The
        expected side is a vectorized groupby (packed int64 codes): the
        full tier sums 500k records, and a per-row Python loop here adds
        seconds to every gated run."""
        ks = np.concatenate([d[0] for d in source._data])
        vs = np.concatenate([d[1] for d in source._data])
        ts = np.concatenate([d[2] for d in source._data])
        ws = (ts // spec.window_ms) * spec.window_ms
        codes = ks.astype(np.int64) * (np.int64(1) << 32) + ws
        uniq, inv = np.unique(codes, return_inverse=True)
        sums = np.bincount(inv, weights=vs)
        expected: Dict[tuple, float] = {
            (int(c >> 32), int(c & 0xFFFFFFFF)): float(s)
            for c, s in zip(uniq.tolist(), sums.tolist())}
        got = {(int(r["k"]), int(r["window_start"])): float(r["feature"])
               for r in committed.get("features", [])}
        viol: List[str] = []
        if len(expected) != len(got):
            viol.append(f"feature ground truth: {len(expected)} expected "
                        f"(key, window) groups vs {len(got)} committed")
        bad = sum(1 for key, s in expected.items()
                  if key not in got or abs(got[key] - s) > 1e-6)
        if bad:
            viol.append(f"feature ground truth: {bad} (key, window) sums "
                        f"diverge from the generated stream")
        return viol
