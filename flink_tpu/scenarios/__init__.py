"""Scenario suite: named end-to-end exactly-once applications under a
diurnal load curve (ROADMAP item 7 / ISSUE-15).

Each scenario composes the subsystems the repo has grown — vectorized
CEP, session windows + SQL, the queryable serving tier, transactional
Kafka sinks, the reactive autoscaler, chaos — into ONE gated workload:

- :mod:`~flink_tpu.scenarios.fraud_detection`: diurnal transaction
  stream -> CEP bait/strike pattern -> transactional alert sink, alerts
  also live-queryable.
- :mod:`~flink_tpu.scenarios.sessionized_analytics`: clickstream ->
  session windows + a tumbling aggregate cross-checked against the SQL
  planner's TUMBLE answer -> transactional sinks.
- :mod:`~flink_tpu.scenarios.feature_store`: high-cardinality window
  aggregates published queryable, read concurrently by routed binary
  clients at a paced QPS while the job runs.

The harness (:mod:`~flink_tpu.scenarios.harness`) owns the lifecycle:
build the job, ramp the shared diurnal generator, let the PR-14
``ReactiveAutoscaler`` react to the peak, inject nemeses DURING the
peak, and verify the committed end-to-end output is exactly-once —
digest-identical to an unfaulted control run over the same generated
stream.  ``bench.py --scenario <name>|all`` gates each scenario against
its ``BENCH_BUDGET.json`` section.
"""

from flink_tpu.scenarios.base import Scenario, ScenarioSpec
from flink_tpu.scenarios.feature_store import FeatureStoreScenario
from flink_tpu.scenarios.fraud_detection import FraudDetectionScenario
from flink_tpu.scenarios.harness import ScenarioHarness
from flink_tpu.scenarios.sessionized_analytics import \
    SessionizedAnalyticsScenario

SCENARIOS = {
    "fraud_detection": FraudDetectionScenario,
    "sessionized_analytics": SessionizedAnalyticsScenario,
    "feature_store": FeatureStoreScenario,
}


def get_scenario(name: str) -> Scenario:
    """Instantiate a scenario by its registered name."""
    try:
        return SCENARIOS[name]()
    except KeyError:
        raise ValueError(f"unknown scenario {name!r} "
                         f"(have: {', '.join(sorted(SCENARIOS))})") from None


__all__ = ["SCENARIOS", "Scenario", "ScenarioHarness", "ScenarioSpec",
           "FeatureStoreScenario", "FraudDetectionScenario",
           "SessionizedAnalyticsScenario", "get_scenario"]
