"""sessionized_analytics scenario: diurnal clickstream -> session windows
AND a tumbling aggregate over the same stream -> transactional Kafka
sinks, with the tumbling branch cross-checked against the SQL planner's
TUMBLE answer over the identical input (the L3/L4 layers must agree on
the same stream).
"""

from __future__ import annotations

from typing import Dict, List, Optional

import numpy as np

from flink_tpu.scenarios.base import Scenario, ScenarioSpec


class SessionizedAnalyticsScenario(Scenario):
    name = "sessionized_analytics"
    budget_section = "scenario_session_cpu"

    def spec(self, smoke: bool, records: Optional[int] = None,
             keys: Optional[int] = None) -> ScenarioSpec:
        return ScenarioSpec(
            name=self.name,
            records=records or (60_000 if smoke else 400_000),
            keys=keys or (1009 if smoke else 50_021),
            batch_size=128 if smoke else 256,
            topics=("sessions", "tumble"),
            queryable_state="session_tumble",
            qps_target=200.0,
            seed=53, smoke=smoke,
            extras={"gap_ms": 500})

    def build(self, env, source, sinks, spec: ScenarioSpec) -> None:
        import jax.numpy as jnp

        from flink_tpu.core.functions import SumAggregator
        from flink_tpu.windowing.assigners import (EventTimeSessionWindows,
                                                   TumblingEventTimeWindows)

        clicks = (env.from_source(source)
                  .assign_timestamps_and_watermarks(0, timestamp_column="t")
                  .key_by("k"))
        # sessionization: per-user activity bursts (gap < window)
        (clicks.window(EventTimeSessionWindows(spec.extras["gap_ms"]))
         .aggregate(SumAggregator(jnp.float64), value_column="v",
                    output_column="s", name="sessionize")
         .add_sink(sinks["sessions"]))
        # the same stream through a TUMBLE aggregate — the datastream twin
        # of the SQL query cross-checked below
        (clicks.window(TumblingEventTimeWindows.of(spec.window_ms))
         .aggregate(SumAggregator(jnp.float64), value_column="v",
                    output_column="s", name="tumble-agg",
                    queryable="session_tumble")
         .add_sink(sinks["tumble"]))

    def cross_check(self, committed: Dict[str, List[dict]], source,
                    spec: ScenarioSpec) -> List[str]:
        """SQL-vs-datastream: replay the SAME generated stream through the
        SQL planner's TUMBLE (``sql/planner.py``) and diff against the
        committed tumbling-branch rows — the two execution layers must
        produce the identical windowed answer."""
        from flink_tpu.sql.table_env import TableEnvironment

        ks = np.concatenate([d[0] for d in source._data])
        vs = np.concatenate([d[1] for d in source._data])
        ts = np.concatenate([d[2] for d in source._data])
        # each split's timestamps are sorted independently; present the
        # union in global time order — the planner's windowed aggregate
        # treats timestamp regressions as late data, exactly like the
        # datastream job would if one source subtask replayed the past
        order = np.argsort(ts, kind="stable")
        ks, vs, ts = ks[order], vs[order], ts[order]
        t_env = TableEnvironment()
        t_env.register_collection(
            "clicks", columns={"k": ks, "v": vs, "ts": ts})
        sec = spec.window_ms // 1000
        rows = t_env.execute_sql(
            f"SELECT k, TUMBLE_START(ts, INTERVAL '{sec}' SECOND) AS ws, "
            f"SUM(v) AS s FROM clicks "
            f"GROUP BY k, TUMBLE(ts, INTERVAL '{sec}' SECOND)").collect()
        sql_answer = {(int(r["k"]), int(r["ws"])): float(r["s"])
                      for r in rows}
        got = {(int(r["k"]), int(r["window_start"])): float(r["s"])
               for r in committed.get("tumble", [])}
        viol: List[str] = []
        if len(sql_answer) != len(got):
            viol.append(f"SQL TUMBLE cross-check: {len(sql_answer)} SQL "
                        f"groups vs {len(got)} committed rows")
        mismatches = sum(
            1 for key, s in sql_answer.items()
            if key not in got or abs(got[key] - s) > 1e-6)
        if mismatches:
            viol.append(f"SQL TUMBLE cross-check: {mismatches} window "
                        f"groups diverge between the SQL planner and the "
                        f"committed datastream output")
        return viol
