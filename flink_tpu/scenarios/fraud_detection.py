"""fraud_detection scenario: diurnal transaction stream -> vectorized CEP
bait/strike pattern -> transactional Kafka alert sink (2PC EOS), alerts
also live-queryable (windowed per-account alert totals).

The pattern is the flink-walkthroughs fraud shape: a SMALL "bait"
transaction followed by a LARGE "strike" on the same account within a
few windows.  ``examples/fraud_detection.py`` imports
:func:`fraud_pattern`/:func:`detect_frauds` so the shipped example and
this gated workload cannot diverge.
"""

from __future__ import annotations

from typing import Optional

import numpy as np

from flink_tpu.scenarios.base import Scenario, ScenarioSpec

#: the bait/strike thresholds over the scenario's uniform [0, 600) amounts
SMALL_MAX = 30.0
LARGE_MIN = 570.0


def fraud_pattern(window_ms: int, amount_column: str = "v"):
    """Bait -> strike on the same key within 4 windows (the shape
    ``bench.py --cep`` benchmarks and the walkthrough example detects)."""
    from flink_tpu.cep import Pattern

    return (Pattern.begin("small")
            .where(lambda c: np.asarray(c[amount_column]) < SMALL_MAX)
            .followed_by("large")
            .where(lambda c: np.asarray(c[amount_column]) > LARGE_MIN)
            .within(4 * window_ms))


def detect_frauds(keyed_stream, window_ms: int, amount_column: str = "v",
                  vectorized: str = "auto"):
    """The scenario's CEP stage over any keyed transaction stream:
    returns the alert DataStream ``{<key>, bait, amount}`` (match
    timestamps ride the batch timestamps)."""
    from flink_tpu.cep import CEP

    key_column = keyed_stream.key_column

    def select_alert(m):
        return {key_column: m["small"][0][key_column],
                "bait": m["small"][0][amount_column],
                "amount": m["large"][0][amount_column]}

    return CEP.pattern(
        keyed_stream,
        fraud_pattern(window_ms, amount_column)).select(
            select_alert, name="fraud-detect", vectorized=vectorized)


class FraudDetectionScenario(Scenario):
    name = "fraud_detection"
    budget_section = "scenario_fraud_cpu"

    def spec(self, smoke: bool, records: Optional[int] = None,
             keys: Optional[int] = None) -> ScenarioSpec:
        return ScenarioSpec(
            name=self.name,
            records=records or (60_000 if smoke else 400_000),
            keys=keys or (997 if smoke else 20_011),
            batch_size=128 if smoke else 256,
            topics=("alerts",),
            queryable_state="fraud_alerts",
            qps_target=200.0,
            seed=47, smoke=smoke)

    def value_fn(self, rng: np.random.Generator, n: int) -> np.ndarray:
        # uniform transaction amounts over [0, 600): ~5% bait, ~5% strike
        return rng.random(n) * 600.0

    def build(self, env, source, sinks, spec: ScenarioSpec) -> None:
        import jax.numpy as jnp

        from flink_tpu.connectors.sinks import FunctionSink
        from flink_tpu.core.functions import SumAggregator
        from flink_tpu.windowing.assigners import TumblingEventTimeWindows

        tx = (env.from_source(source)
              .assign_timestamps_and_watermarks(0, timestamp_column="t")
              .key_by("k"))
        alerts = detect_frauds(tx, spec.window_ms)
        # committed end-to-end output: every alert exactly once
        alerts.add_sink(sinks["alerts"])
        # live-queryable per-account alert totals (windowed so fires — and
        # therefore live-view publishes — happen continuously)
        (alerts.key_by("k")
         .window(TumblingEventTimeWindows.of(spec.window_ms * 4))
         .aggregate(SumAggregator(jnp.float64), value_column="amount",
                    output_column="alert_amount",
                    queryable="fraud_alerts")
         .add_sink(FunctionSink(lambda b: None)))
