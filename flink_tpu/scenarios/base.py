"""Scenario protocol: what a named end-to-end application declares.

A scenario is a topology + a sized workload; the harness
(:mod:`flink_tpu.scenarios.harness`) owns everything operational (broker,
autoscaler, chaos, queryable readers, verification).  Keeping the two
apart means ``examples/`` can reuse a scenario's topology pieces without
dragging the harness in, and the harness can drive any scenario the same
way.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.testing.workload import DiurnalSource


@dataclass
class ScenarioSpec:
    """One sized instantiation of a scenario (smoke vs full)."""

    name: str
    records: int
    keys: int
    batch_size: int = 128
    span_ms: int = 20_000
    window_ms: int = 1000
    peak_s: float = 0.004
    trough_s: float = 0.020
    seed: int = 47
    topics: Tuple[str, ...] = ()
    queryable_state: Optional[str] = None
    #: paced lookups/sec the harness's routed binary clients sustain
    #: against ``queryable_state`` while the job runs (0 = no read leg)
    qps_target: float = 0.0
    qps_batch_keys: int = 64
    smoke: bool = False
    extras: Dict[str, Any] = field(default_factory=dict)


class Scenario:
    """Base scenario: subclasses declare the topology and sizes.

    Contract:

    - ``spec(smoke, records=, keys=)`` -> :class:`ScenarioSpec`
    - ``build(env, source, sinks, spec)`` — wire the topology onto the
      environment; ``sinks`` maps each declared topic to a fresh
      transactional sink.
    - ``value_fn(rng, n)`` — the value column's distribution (defaults to
      all ones: summed outputs stay exact in float64, the digest
      convention).
    - ``cross_check(committed, source, spec)`` — scenario-specific output
      validation beyond the control-digest comparison (e.g. the SQL
      TUMBLE cross-check); returns a list of violation strings.
    - ``nemeses(injector, spec, full)`` — arm the chaos schedules to
      inject AT THE PEAK; returns the armed schedules keyed by name
      (``full=True`` adds the heavyweight nemeses the quick tier skips).
    """

    name: str = "scenario"
    budget_section: str = "scenario_cpu"

    def spec(self, smoke: bool, records: Optional[int] = None,
             keys: Optional[int] = None) -> ScenarioSpec:
        raise NotImplementedError

    # -- workload ----------------------------------------------------------
    def value_fn(self, rng: np.random.Generator, n: int) -> np.ndarray:
        return np.ones(n, np.float64)

    def make_source(self, spec: ScenarioSpec,
                    paced: bool = True) -> DiurnalSource:
        """A FRESH diurnal source for one leg — same seed => bit-identical
        stream, so the faulted run and the unfaulted control see the same
        input."""
        return DiurnalSource(spec.records, spec.keys, spec.batch_size,
                             spec.span_ms, peak_s=spec.peak_s,
                             trough_s=spec.trough_s, seed=spec.seed,
                             value_fn=self.value_fn, paced=paced)

    # -- topology ----------------------------------------------------------
    def build(self, env, source, sinks: Dict[str, Any],
              spec: ScenarioSpec) -> None:
        raise NotImplementedError

    def plan(self, parallelism: int, source, sinks: Dict[str, Any],
             spec: ScenarioSpec):
        from flink_tpu.datastream.api import StreamExecutionEnvironment

        env = StreamExecutionEnvironment()
        env.set_parallelism(parallelism)
        self.build(env, source, sinks, spec)
        return env.get_stream_graph(f"scenario-{self.name}").to_plan()

    # -- chaos at the peak -------------------------------------------------
    def nemeses(self, injector, spec: ScenarioSpec,
                full: bool = False) -> Dict[str, Any]:
        """Default nemesis set, armed when the curve reaches its peak: a
        worker kill (one subtask dies mid-stream -> region restart from
        the last cut), bursty ``SlowConsumer`` drain stalls, and a
        ``KillDuringRescale`` priming the NEXT rescale's redistribute to
        die (absorbed by the lifecycle's idempotent re-trigger).
        ``full=True`` adds ``WedgedDevice`` on the hot-path dispatch —
        the watchdog/quarantine/degrade path — which costs seconds of
        wall clock and is reserved for the bench tier."""
        from flink_tpu.testing import chaos

        armed = {
            "worker_kill": injector.inject(
                "subtask.run", chaos.FailTimes(1, message="scenario "
                                               "worker kill at peak")),
            "kill_during_rescale": injector.inject(
                "rescale.redistribute", chaos.KillDuringRescale(at=1)),
        }
        # (the SlowConsumer leg rides the harness's consumer-cost schedule
        # on ``channel.recv`` — one point holds one schedule, so the
        # harness arms its burst mode rather than replacing the cost)
        if full:
            armed["wedged_device"] = injector.inject(
                "device.dispatch", chaos.WedgedDevice(at=1))
        return armed

    # -- verification ------------------------------------------------------
    def cross_check(self, committed: Dict[str, List[dict]], source,
                    spec: ScenarioSpec) -> List[str]:
        return []
