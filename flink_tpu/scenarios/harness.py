"""Scenario lifecycle harness (ISSUE-15 tentpole).

One :class:`ScenarioHarness` run is two legs over the SAME generated
diurnal stream:

1. **Faulted leg** — the real production shape: the scenario's topology
   (transactional Kafka sinks, queryable operators) runs under the PR-14
   :class:`ReactiveAutoscaler`; a fixed per-dequeue consumer cost makes
   drain capacity proportional to parallelism, so the diurnal peak
   backpressures the job and the autoscaler rescales through unaligned
   cuts with channel-state redistribution.  When the curve reaches its
   peak the scenario's nemeses arm (worker kill, SlowConsumer bursts,
   ``KillDuringRescale``; the bench tier adds ``WedgedDevice``).  If the
   scenario publishes queryable state, routed binary
   ``QueryableStateClientPool`` readers (PR-13) sustain a paced QPS
   against the RUNNING job, reconnecting across rescales.
2. **Control leg** — the same scenario and a bit-identical fresh source
   (same seed), unpaced, fixed parallelism, no chaos.

Verification: per-topic COMMITTED rows (the broker only exposes
EndTxn-committed transactions — read-committed semantics) are compared
as multisets: missing rows = lost, extra rows = duplicated, and the
canonical digests must match exactly; scenario ``cross_check`` hooks add
ground-truth checks (e.g. sessionized_analytics replays the stream
through the SQL planner's TUMBLE and diffs the answers).
"""

from __future__ import annotations

import hashlib
import json
import os
import shutil
import tempfile
import threading
import time
from collections import Counter
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from flink_tpu.scenarios.base import Scenario, ScenarioSpec
from flink_tpu.testing import chaos

#: curve fraction at which the peak nemeses arm (the sine curve's upslope
#: shoulder: backpressure is building, the autoscaler's first scale-out
#: lands around here)
PEAK_ARM_FRAC = 0.35


class _ConsumerCost(chaos.FaultSchedule):
    """Fixed per-dequeue cost on ``channel.recv`` — the consumer-cost
    model that makes drain capacity proportional to the number of
    consuming subtasks (the reason scale-out helps) — plus, once
    :meth:`arm` fires at the peak, a bursty :class:`SlowConsumer` riding
    the SAME point (one point holds one schedule)."""

    def __init__(self, cost_s: float, slow: chaos.SlowConsumer):
        self.cost_s = cost_s
        self.slow = slow
        self._armed = threading.Event()

    def arm(self) -> None:
        self._armed.set()

    def matches(self, ctx) -> bool:
        return True

    def action(self, n, rng):
        extra = 0.0
        if self._armed.is_set():
            act = self.slow.action(n, rng)
            if isinstance(act, tuple) and act[0] == "delay":
                extra = act[1]
        return ("delay", self.cost_s + extra)


class _QueryableReader:
    """Paced routed-binary read leg against the running job's queryable
    state (the PR-13 client threaded into the scenarios — the named
    ISSUE-13 headroom item).  Tolerates rescales: when the autoscaler
    swaps clusters the old server goes dark; the reader evicts its pool,
    starts the new cluster's server and reconnects."""

    def __init__(self, scaler, spec: ScenarioSpec):
        self.scaler = scaler
        self.spec = spec
        self.stats = {"lookups": 0, "found": 0, "batches": 0, "errors": 0,
                      "reconnects": 0, "routed_batches": 0,
                      "json_fallbacks": 0}
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True,
                                        name=f"scenario-reader-{spec.name}")
        rng = np.random.default_rng(spec.seed + 1)
        self._keys = rng.integers(0, spec.keys,
                                  spec.qps_batch_keys).astype(np.int64)
        self._wall_s = 0.0

    def start(self) -> "_QueryableReader":
        self._thread.start()
        return self

    def stop(self) -> Dict[str, Any]:
        self._stop.set()
        self._thread.join(timeout=10)
        out = dict(self.stats)
        out["lookups_per_sec"] = round(
            self.stats["lookups"] / self._wall_s, 1) if self._wall_s else 0.0
        return out

    def _run(self) -> None:
        from flink_tpu.queryable import QueryableStateClientPool

        interval = (self.spec.qps_batch_keys / self.spec.qps_target
                    if self.spec.qps_target > 0 else 0.05)
        pool: Optional[QueryableStateClientPool] = None
        bound_cluster = None
        t0 = time.monotonic()
        try:
            while not self._stop.is_set():
                cluster = getattr(self.scaler, "_cluster", None)
                if cluster is None or cluster.queryable is None:
                    time.sleep(0.05)
                    continue
                try:
                    if cluster is not bound_cluster:
                        if pool is not None:
                            self._harvest(pool)
                            pool.close()
                            pool = None
                            self.stats["reconnects"] += 1
                        if bound_cluster is not None \
                                and bound_cluster.queryable is not None:
                            # the superseded incarnation is cancelled; its
                            # serving threads must not outlive it
                            try:
                                bound_cluster.queryable.close()
                            except Exception:  # noqa: BLE001
                                pass
                        server = cluster.start_queryable_server()
                        pool = QueryableStateClientPool(
                            server.host, server.port, protocol="auto",
                            routing=True, timeout_s=2.0)
                        bound_cluster = cluster
                    t_req = time.monotonic()
                    ans = pool.get_batch(self.spec.queryable_state,
                                         self._keys, consistency="live")
                    self.stats["lookups"] += int(self._keys.size)
                    self.stats["batches"] += 1
                    self.stats["found"] += int(sum(ans["found"]))
                except Exception:  # noqa: BLE001 — rescale windows sever us
                    self.stats["errors"] += 1
                    bound_cluster = None
                    time.sleep(0.05)
                    continue
                sleep_left = interval - (time.monotonic() - t_req)
                if sleep_left > 0:
                    time.sleep(sleep_left)
        finally:
            self._wall_s = time.monotonic() - t0
            if pool is not None:
                self._harvest(pool)
                try:
                    pool.close()
                except Exception:  # noqa: BLE001
                    pass

    def _harvest(self, pool) -> None:
        self.stats["routed_batches"] += pool.stats.get("routed_batches", 0)
        # 0 fallbacks == every routed batch rode the binary columnar wire
        self.stats["json_fallbacks"] += pool.stats.get("json_fallbacks", 0)


def canonical_rows(rows: List[dict]) -> List[str]:
    """Order-insensitive canonical form of committed sink rows."""
    return sorted(json.dumps(r, sort_keys=True) for r in rows)


def committed_digest(committed: Dict[str, List[dict]]) -> str:
    h = hashlib.sha256()
    for topic in sorted(committed):
        h.update(topic.encode())
        for line in canonical_rows(committed[topic]):
            h.update(line.encode())
            h.update(b"\n")
    return h.hexdigest()


def diff_committed(faulted: Dict[str, List[dict]],
                   control: Dict[str, List[dict]]) -> Tuple[int, int]:
    """(lost, duplicated) across all topics: rows the control committed
    that the faulted run did not (lost), and rows the faulted run
    committed beyond the control's multiset (duplicated)."""
    lost = dup = 0
    for topic in set(faulted) | set(control):
        fc = Counter(canonical_rows(faulted.get(topic, [])))
        cc = Counter(canonical_rows(control.get(topic, [])))
        lost += sum((cc - fc).values())
        dup += sum((fc - cc).values())
    return lost, dup


def consume_topic(broker, topic: str, partitions: int = 1) -> List[dict]:
    """All COMMITTED rows of a topic (staged transactions are invisible
    until EndTxn commit — the broker IS read-committed)."""
    from flink_tpu.connectors.kafka import KafkaWireClient

    c = KafkaWireClient(broker.host, broker.port)
    try:
        out: List[dict] = []
        for p in range(partitions):
            hw = c.latest_offset(topic, p)
            off = 0
            while off < hw:
                msgs, _ = c.fetch(topic, p, off)
                if not msgs:
                    break
                for o, _k, v in msgs:
                    if o >= hw:
                        break
                    if v:
                        out.append(json.loads(v.decode()))
                    off = o + 1
        return out
    finally:
        c.close()


class LegResult:
    def __init__(self):
        self.state: str = "Unknown"
        self.error: Optional[str] = None
        self.committed: Dict[str, List[dict]] = {}
        self.source = None
        self.rescales = 0
        self.rollbacks = 0
        self.retriggers = 0
        self.parallelism_path: List[int] = []
        self.peak: Dict[str, float] = {}
        self.latency_p99_ms: Optional[float] = None
        self.nemeses: List[str] = []
        self.queryable: Dict[str, Any] = {}
        self.wall_ms: float = 0.0


class ScenarioHarness:
    """Drives one scenario end to end; see the module docstring."""

    def __init__(self, scenario: Scenario, smoke: bool = False,
                 records: Optional[int] = None, keys: Optional[int] = None,
                 base_dir: Optional[str] = None,
                 full_nemeses: bool = False,
                 consumer_cost_s: float = 0.010,
                 job_timeout_s: float = 600.0):
        self.scenario = scenario
        self.spec = scenario.spec(smoke, records=records, keys=keys)
        self._own_dir = base_dir is None
        self.base_dir = base_dir or tempfile.mkdtemp(
            prefix=f"scenario-{scenario.name}-")
        self.full_nemeses = full_nemeses
        self.consumer_cost_s = consumer_cost_s
        self.job_timeout_s = job_timeout_s

    # -- legs --------------------------------------------------------------
    def _make_sinks(self, broker) -> Dict[str, Any]:
        from flink_tpu.connectors.kafka import KafkaExactlyOnceSink

        return {t: KafkaExactlyOnceSink(
                    broker.host, broker.port, t,
                    sink_id=f"{self.scenario.name}-{t}", buffer_rows=512)
                for t in self.spec.topics}

    def _run_faulted(self) -> LegResult:
        from flink_tpu.cluster.adaptive import (AutoscalerPolicy,
                                                ReactiveAutoscaler)
        from flink_tpu.connectors.kafka import KafkaWireBroker
        from flink_tpu.runtime.checkpoint.storage import \
            InMemoryCheckpointStorage

        res = LegResult()
        spec = self.spec
        broker = KafkaWireBroker(
            directory=os.path.join(self.base_dir, "faulted-kafka")).start()
        try:
            for t in spec.topics:
                broker.create_topic(t, partitions=1)
            source = self.scenario.make_source(spec, paced=True)
            res.source = source

            def plan_factory(parallelism):
                return self.scenario.plan(parallelism, source,
                                          self._make_sinks(broker), spec)

            policy = AutoscalerPolicy(
                min_parallelism=2, max_parallelism=4,
                scale_out_queue_depth=12, scale_in_queue_depth=2,
                sustain_polls=3, cooldown_ms=1500.0)
            scaler = ReactiveAutoscaler(
                plan_factory,
                checkpoint_storage=InMemoryCheckpointStorage(retain=10),
                policy=policy, initial_parallelism=2,
                poll_interval_ms=25.0, checkpoint_interval_ms=50,
                alignment_timeout_ms=100.0, restart_attempts=4,
                job_timeout_s=self.job_timeout_s,
                latency_interval_ms=50,
                # ISSUE-16: sub-second cuts stay affordable because delta
                # tracking ships increment bytes ∝ change rate — the 2PC
                # commit cadence stops being bounded by full-state bytes
                incremental=True)
            inj = chaos.FaultInjector(seed=spec.seed)
            cost = _ConsumerCost(
                self.consumer_cost_s,
                chaos.SlowConsumer(max_s=0.03, min_s=0.01, p=0.1, burst=8,
                                   times=400))
            inj.inject("channel.recv", cost)
            armed: Dict[str, Any] = {}
            reader = (_QueryableReader(scaler, spec).start()
                      if spec.queryable_state and spec.qps_target > 0
                      else None)
            p99_max: Optional[float] = None
            stop = threading.Event()

            def watch():
                nonlocal p99_max, armed
                wedge_seen_at: Optional[float] = None
                while not stop.is_set():
                    st = scaler.status()
                    p99 = st["signals"].get("latency_p99_ms")
                    if p99 is not None:
                        p99_max = p99 if p99_max is None \
                            else max(p99_max, p99)
                    if not armed \
                            and source.progress_frac() >= PEAK_ARM_FRAC:
                        armed = self.scenario.nemeses(
                            inj, spec, full=self.full_nemeses)
                        cost.arm()
                        armed["slow_consumer"] = cost.slow
                    wedge = armed.get("wedged_device")
                    if wedge is not None and wedge.wedged_once \
                            and not wedge.healed:
                        # give the watchdog time to quarantine, then heal
                        # so the background healer can re-promote
                        if wedge_seen_at is None:
                            wedge_seen_at = time.monotonic()
                        elif time.monotonic() - wedge_seen_at > 2.0:
                            wedge.heal()
                    time.sleep(0.05)

            t0 = time.monotonic()
            watcher = threading.Thread(target=watch, daemon=True)
            watcher.start()
            try:
                with chaos.installed(inj):
                    scaler.start()
                    scaler.join(timeout_s=self.job_timeout_s + 60)
            finally:
                stop.set()
                wedge = armed.get("wedged_device")
                if wedge is not None and not wedge.healed:
                    wedge.heal()            # release any parked sacrifice
                if scaler.state not in ("Finished", "Failed", "Canceled"):
                    scaler.cancel()
                watcher.join(timeout=5)
                if reader is not None:
                    res.queryable = reader.stop()
                cluster = getattr(scaler, "_cluster", None)
                if cluster is not None and cluster.queryable is not None:
                    cluster.queryable.close()
            res.wall_ms = round((time.monotonic() - t0) * 1000.0, 1)
            st = scaler.status()
            res.state = scaler.state
            res.error = scaler.error
            res.rescales = st["rescales"]
            res.rollbacks = st["rollbacks"]
            res.retriggers = st["retriggers"]
            res.parallelism_path = st["parallelism_path"]
            res.latency_p99_ms = p99_max
            res.peak = source.peak_stats()
            res.nemeses = sorted(armed)
            res.committed = {t: consume_topic(broker, t)
                             for t in spec.topics}
        finally:
            broker.stop()
        return res

    def _run_control(self) -> LegResult:
        from flink_tpu.cluster.minicluster import MiniCluster
        from flink_tpu.connectors.kafka import KafkaWireBroker
        from flink_tpu.runtime.checkpoint.storage import \
            InMemoryCheckpointStorage

        res = LegResult()
        spec = self.spec
        broker = KafkaWireBroker(
            directory=os.path.join(self.base_dir, "control-kafka")).start()
        try:
            for t in spec.topics:
                broker.create_topic(t, partitions=1)
            source = self.scenario.make_source(spec, paced=False)
            res.source = source
            plan = self.scenario.plan(2, source, self._make_sinks(broker),
                                      spec)
            cluster = MiniCluster(
                checkpoint_storage=InMemoryCheckpointStorage(retain=5),
                checkpoint_interval_ms=50, alignment_timeout_ms=100.0,
                restart_attempts=2, incremental=True)
            t0 = time.monotonic()
            try:
                out = cluster.execute(plan, timeout_s=self.job_timeout_s)
                res.state = ("Finished" if out.state == "FINISHED"
                             else str(out.state).title())
                res.error = getattr(out, "error", None)
            finally:
                if cluster.queryable is not None:
                    cluster.queryable.close()
            res.wall_ms = round((time.monotonic() - t0) * 1000.0, 1)
            res.parallelism_path = [2]
            res.committed = {t: consume_topic(broker, t)
                             for t in spec.topics}
        finally:
            broker.stop()
        return res

    # -- coordinator HA: kill the leader at the peak (ISSUE-20) ------------
    def run_ha_kill(self) -> Dict[str, Any]:
        """Coordinator-kill leg: leader A (epoch e) runs the scenario under
        a :class:`FileHaStore` lease; at the diurnal peak a
        ``KillCoordinator`` nemesis fails A's next lease renewal (loud
        demotion — A becomes a ZOMBIE that keeps executing), standby B
        acquires the lease at e+1, proves A's stale-epoch checkpoint
        completions are fenced by the HA store, recovers the job from the
        completed-checkpoint pointer (increment chains included) and runs
        it to completion.  Verified exactly like :meth:`run`: committed
        rows vs an unfaulted control leg — zero lost, zero duplicated,
        digest-identical — plus two unconditional fencing probes
        (``stale_pointer_rejected``, ``stale_commit_fenced``)."""
        from flink_tpu.cluster.minicluster import MiniCluster
        from flink_tpu.connectors.kafka import (KafkaExactlyOnceSink,
                                                KafkaWireBroker)
        from flink_tpu.runtime import ha as ha_mod
        from flink_tpu.runtime.checkpoint.incremental import \
            IncrementalCheckpointStorage

        spec = self.spec
        t_total = time.monotonic()
        result: Dict[str, Any] = {
            "scenario": self.scenario.name, "mode": "ha-kill",
            "smoke": spec.smoke, "records": spec.records, "keys": spec.keys,
        }
        try:
            broker = KafkaWireBroker(
                directory=os.path.join(self.base_dir, "ha-kafka")).start()
            try:
                for t in spec.topics:
                    broker.create_topic(t, partitions=1)
                store = ha_mod.FileHaStore(
                    os.path.join(self.base_dir, "ha-store"))
                storage = IncrementalCheckpointStorage(
                    os.path.join(self.base_dir, "ha-ckpts"), retain=6,
                    max_increments_per_base=4, compact_in_background=False)
                job_id = ha_mod.job_id_for(f"scenario-{self.scenario.name}")
                # satellite 2: retention (A's AND B's — shared storage)
                # never evicts the pointed-at cut, whole chain included
                storage.pin_provider = lambda: (
                    (store.completed_checkpoint(job_id) or {})
                    .get("checkpoint_id"))
                source = self.scenario.make_source(spec, paced=True)
                ttl = 0.75

                def make_cluster(epoch: int) -> MiniCluster:
                    c = MiniCluster(
                        checkpoint_storage=storage,
                        checkpoint_interval_ms=50,
                        alignment_timeout_ms=100.0,
                        restart_attempts=2, incremental=True)
                    # epoch-partitioned checkpoint ids: the zombie and the
                    # new leader share one directory without colliding
                    c._next_checkpoint_id = (epoch - 1) * 1_000_000 + 1

                    def gate(cid: int, _e: int = epoch) -> bool:
                        # the decisive fence: advancing the HA pointer
                        # re-verifies the store's leader epoch — a zombie
                        # fails HERE, before any notify fans out
                        try:
                            store.set_completed_checkpoint(job_id, cid, _e)
                            return True
                        except ha_mod.StaleEpochError:
                            return False
                    c.ha_commit_gate = gate
                    return c

                inj = chaos.FaultInjector(seed=spec.seed)
                with chaos.installed(inj):
                    # -- leader A (epoch e) ---------------------------------
                    lease_a = store.acquire(f"leader-A-{os.getpid()}", ttl)
                    store.register_job(
                        job_id, {"scenario": self.scenario.name,
                                 "parallelism": 2}, lease_a.epoch)
                    demoted = threading.Event()
                    t_demote = [0.0]

                    def on_lost(exc: Exception) -> None:
                        t_demote[0] = time.monotonic()
                        demoted.set()

                    renewer_a = ha_mod.LeaseRenewer(
                        store, lease_a, ttl, on_lost=on_lost).start()
                    cluster_a = make_cluster(lease_a.epoch)
                    plan_a = self.scenario.plan(
                        2, source, self._make_sinks(broker), spec)
                    a_out: Dict[str, Any] = {}

                    def run_a() -> None:
                        try:
                            r = cluster_a.execute(
                                plan_a, timeout_s=self.job_timeout_s)
                            a_out["state"] = str(r.state)
                        except Exception as e:  # noqa: BLE001 — zombie dies
                            a_out["error"] = f"{type(e).__name__}: {e}"

                    thread_a = threading.Thread(target=run_a, daemon=True,
                                                name="ha-leader-A")
                    thread_a.start()
                    # arm the kill at the peak, once at least one cut has
                    # published a pointer (something to recover FROM)
                    deadline = time.monotonic() + self.job_timeout_s
                    while time.monotonic() < deadline and (
                            source.progress_frac() < PEAK_ARM_FRAC
                            or store.completed_checkpoint(job_id) is None):
                        time.sleep(0.02)
                    inj.inject("ha.lease", chaos.KillCoordinator(at=1))
                    demoted.wait(timeout=30)
                    renewer_a.stop()
                    renewer_a.join()
                    if not t_demote[0]:
                        t_demote[0] = time.monotonic()

                    # -- standby B takes over at epoch e+1 ------------------
                    lease_b = store.acquire(f"leader-B-{os.getpid()}", ttl,
                                            timeout_s=60.0)
                    renewer_b = ha_mod.LeaseRenewer(store, lease_b,
                                                    ttl).start()
                    # zombie probe: A is STILL RUNNING — its next
                    # completion must bounce off the store's epoch fence
                    probe_deadline = time.monotonic() + 10.0
                    while (cluster_a.ha_fenced_completions == 0
                           and thread_a.is_alive()
                           and time.monotonic() < probe_deadline):
                        time.sleep(0.02)
                    stale_pointer_rejected = \
                        cluster_a.ha_fenced_completions > 0
                    pointer = store.completed_checkpoint(job_id)
                    # stand the zombie down before the new incarnation
                    # deploys (its open transactions get swept by B's
                    # restore anyway)
                    cluster_a.cancel()
                    thread_a.join(timeout=60)

                    snap, restore_source = ha_mod.resolve_restore(
                        store, job_id, storage)
                    registered = store.load_job(job_id)
                    cluster_b = make_cluster(lease_b.epoch)
                    plan_b = self.scenario.plan(
                        int(registered.get("parallelism", 2)), source,
                        self._make_sinks(broker), spec)
                    b_out: Dict[str, Any] = {}

                    def run_b() -> None:
                        try:
                            r = cluster_b.execute(
                                plan_b, restore=snap,
                                timeout_s=self.job_timeout_s)
                            b_out["state"] = str(r.state)
                        except Exception as e:  # noqa: BLE001
                            b_out["error"] = f"{type(e).__name__}: {e}"

                    thread_b = threading.Thread(target=run_b, daemon=True,
                                                name="ha-leader-B")
                    thread_b.start()
                    # recovered = the NEW epoch completes a cut of its own
                    recover_deadline = time.monotonic() + self.job_timeout_s
                    while time.monotonic() < recover_deadline:
                        ptr = store.completed_checkpoint(job_id)
                        if ptr is not None and ptr["epoch"] >= lease_b.epoch:
                            break
                        if not thread_b.is_alive():
                            break
                        time.sleep(0.02)
                    recovery_ms = round(
                        (time.monotonic() - t_demote[0]) * 1000.0, 1)
                    thread_b.join(timeout=self.job_timeout_s + 60)
                    renewer_b.stop()
                    renewer_b.join()

                    # unconditional 2PC fence probe on a side topic (never
                    # part of the digest): a staged transaction notified
                    # under the OLD epoch must not commit
                    broker.create_topic("ha-probe", partitions=1)
                    psink = KafkaExactlyOnceSink(
                        broker.host, broker.port, "ha-probe",
                        sink_id="ha-probe", buffer_rows=4)
                    try:
                        h = tuple(psink.begin_transaction(psink.txn_name(0)))
                        psink.write_rows(h, [{"probe": 1}])
                        psink.pre_commit(h)
                        psink._staged.append((h, 1))
                        psink.fence_epoch = lease_b.epoch
                        psink.notify_checkpoint_complete(
                            1, epoch=lease_a.epoch)
                        fenced_nothing = (
                            psink.fenced_commits == 1
                            and not consume_topic(broker, "ha-probe"))
                        psink.notify_checkpoint_complete(
                            1, epoch=lease_b.epoch)
                        stale_commit_fenced = (
                            fenced_nothing
                            and len(consume_topic(broker, "ha-probe")) == 1)
                    finally:
                        psink.close()

                faulted_committed = {t: consume_topic(broker, t)
                                     for t in spec.topics}
                result.update({
                    "state": b_out.get("state", b_out.get("error",
                                                          "Unknown")),
                    "zombie_state": a_out.get("state",
                                              a_out.get("error", "Unknown")),
                    "leader_epochs": [lease_a.epoch, lease_b.epoch],
                    "recovery_ms": recovery_ms,
                    "restore_source": restore_source,
                    "fenced_completions": cluster_a.ha_fenced_completions,
                    "stale_pointer_rejected": bool(stale_pointer_rejected),
                    "stale_commit_fenced": bool(stale_commit_fenced),
                    "pointer": pointer,
                })
            finally:
                broker.stop()

            control = self._run_control()
            lost, dup = diff_committed(faulted_committed, control.committed)
            f_digest = committed_digest(faulted_committed)
            c_digest = committed_digest(control.committed)
            committed_total = sum(len(r) for r in faulted_committed.values())
            result.update({
                "control_state": control.state,
                "control_error": control.error,
                "records_lost": int(lost),
                "records_duplicated": int(dup),
                "digest_match": f_digest == c_digest,
                "committed_rows": {t: len(r)
                                   for t, r in faulted_committed.items()},
                "control_rows": {t: len(r)
                                 for t, r in control.committed.items()},
                "ok": bool(result.get("state") == "FINISHED"
                           and control.state == "Finished"
                           and lost == 0 and dup == 0
                           and f_digest == c_digest and committed_total > 0
                           and result["stale_pointer_rejected"]
                           and result["stale_commit_fenced"]
                           and result["leader_epochs"][1]
                           > result["leader_epochs"][0]),
            })
        finally:
            if self._own_dir:
                shutil.rmtree(self.base_dir, ignore_errors=True)
        result["wall_ms"] = round((time.monotonic() - t_total) * 1000.0, 1)
        return result

    # -- the whole scenario ------------------------------------------------
    def run(self) -> Dict[str, Any]:
        spec = self.spec
        t0 = time.monotonic()
        try:
            faulted = self._run_faulted()
            control = self._run_control()
        finally:
            if self._own_dir:
                shutil.rmtree(self.base_dir, ignore_errors=True)
        lost, dup = diff_committed(faulted.committed, control.committed)
        f_digest = committed_digest(faulted.committed)
        c_digest = committed_digest(control.committed)
        cross = self.scenario.cross_check(faulted.committed, faulted.source,
                                          spec)
        cross += [f"control: {v}"
                  for v in self.scenario.cross_check(
                      control.committed, control.source, spec)]
        committed_total = sum(len(r) for r in faulted.committed.values())
        ok = (faulted.state == "Finished" and control.state == "Finished"
              and lost == 0 and dup == 0 and f_digest == c_digest
              and committed_total > 0 and not cross)
        result: Dict[str, Any] = {
            "scenario": self.scenario.name,
            "ok": bool(ok),
            "smoke": spec.smoke,
            "records": spec.records,
            "keys": spec.keys,
            "state": faulted.state,
            "error": faulted.error,
            "control_state": control.state,
            "control_error": control.error,
            "rescales": faulted.rescales,
            "rollbacks": faulted.rollbacks,
            "retriggers": faulted.retriggers,
            "parallelism_path": faulted.parallelism_path,
            "nemeses": faulted.nemeses,
            "peak_records_per_sec": faulted.peak.get(
                "peak_records_per_sec", 0.0),
            "latency_p99_ms": faulted.latency_p99_ms,
            "records_lost": int(lost),
            "records_duplicated": int(dup),
            "digest_match": f_digest == c_digest,
            "committed_rows": {t: len(r)
                               for t, r in faulted.committed.items()},
            "control_rows": {t: len(r)
                             for t, r in control.committed.items()},
            "cross_check_violations": cross,
            "queryable": faulted.queryable,
            "wall_ms": round((time.monotonic() - t0) * 1000.0, 1),
        }
        return result
