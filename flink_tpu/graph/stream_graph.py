"""Transformation DAG -> StreamGraph -> ExecutionPlan (with operator chaining).

Mirrors the two-step translation of the reference —
``StreamGraphGenerator.java:122`` (API DAG -> stream graph) and
``StreamingJobGraphGenerator.java:161`` (chaining decision ``isChainable:403``,
job graph) — collapsed into one pass: transformations become ``StreamNode``s;
consecutive FORWARD edges whose endpoints agree on parallelism fuse into a
``ChainedOperator`` (the zero-serialization direct-call path the reference
gets from ``OperatorChain.java:88``; on TPU the chained step functions
additionally jit-fuse because stateless chained ops are jax-traceable).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from flink_tpu.graph.transformations import Partitioning, Transformation
from flink_tpu.operators.base import StreamOperator
from flink_tpu.operators.chain import ChainedOperator


@dataclass
class StreamEdge:
    source_id: int
    target_id: int
    partitioning: str
    key_column: Optional[str] = None
    #: which logical input port of the target this edge feeds (two-input
    #: operators: 0 = left/main, 1 = right/broadcast side)
    input_index: int = 0


@dataclass
class StreamNode:
    id: int
    name: str
    transformation: Transformation
    parallelism: int
    max_parallelism: int
    in_edges: List[StreamEdge] = field(default_factory=list)
    out_edges: List[StreamEdge] = field(default_factory=list)


@dataclass
class PlanVertex:
    """One schedulable vertex: a chain of transformations run as one operator."""

    id: int
    name: str
    chain: List[Transformation]
    parallelism: int
    max_parallelism: int
    is_source: bool
    out_edges: List[StreamEdge] = field(default_factory=list)  # target = vertex id
    in_degree: int = 0
    topo_index: int = -1  # assigned by ExecutionPlan; stable across rebuilds

    def build_operator(self) -> StreamOperator:
        ops = [t.operator_factory() for t in self.chain if t.operator_factory]
        if len(ops) == 1:
            return ops[0]
        return ChainedOperator(ops, name=self.name)

    @property
    def uid(self) -> str:
        """Stable operator id for snapshot mapping (``uid()`` analog): an
        explicit uid on any chain member wins; otherwise topo-position + chain
        name, which is identical for identically-built pipelines (unlike the
        process-global transformation counter)."""
        for t in self.chain:
            if t.uid:
                return t.uid
        return f"v{self.topo_index}:{self.name}"


class StreamGraph:
    def __init__(self, nodes: Dict[int, StreamNode], default_parallelism: int,
                 default_max_parallelism: int, job_name: str = "job"):
        self.nodes = nodes
        self.default_parallelism = default_parallelism
        self.default_max_parallelism = default_max_parallelism
        self.job_name = job_name

    @staticmethod
    def from_sinks(sinks: List[Transformation], default_parallelism: int = 1,
                   default_max_parallelism: int = 128,
                   job_name: str = "job") -> "StreamGraph":
        all_t: Dict[int, Transformation] = {}
        for s in sinks:
            for t in s.all_upstream():
                all_t[t.id] = t
        nodes = {
            t.id: StreamNode(
                id=t.id, name=t.name, transformation=t,
                parallelism=t.parallelism or default_parallelism,
                max_parallelism=t.max_parallelism or default_max_parallelism,
            )
            for t in all_t.values()
        }
        for t in all_t.values():
            for idx, inp in enumerate(t.inputs):
                part = t.partitioning
                key_col = t.key_column
                if t.input_partitionings is not None:
                    part = t.input_partitionings[idx]
                if t.input_key_columns is not None:
                    key_col = t.input_key_columns[idx]
                e = StreamEdge(inp.id, t.id, part, key_col, input_index=idx)
                nodes[inp.id].out_edges.append(e)
                nodes[t.id].in_edges.append(e)
        return StreamGraph(nodes, default_parallelism, default_max_parallelism,
                           job_name)

    # -- chaining ------------------------------------------------------------
    def _chainable(self, edge: StreamEdge) -> bool:
        """``StreamingJobGraphGenerator.isChainable:403`` analog."""
        up, down = self.nodes[edge.source_id], self.nodes[edge.target_id]
        return (
            edge.partitioning == Partitioning.FORWARD
            and up.parallelism == down.parallelism
            and len(down.in_edges) == 1
            and len(up.out_edges) == 1
            and down.transformation.chainable
            and up.transformation.chainable
        )

    def to_plan(self) -> "ExecutionPlan":
        # heads: nodes whose (single) in-edge is not chainable, or sources/joins
        heads: List[StreamNode] = []
        chained_into: Dict[int, int] = {}  # node id -> head id
        for n in self.nodes.values():
            if not n.in_edges or not all(self._chainable(e) for e in n.in_edges):
                heads.append(n)
        # follow chainable out-edges from each head
        vertices: Dict[int, PlanVertex] = {}
        for head in heads:
            chain = [head.transformation]
            chained_into[head.id] = head.id
            cur = head
            while (len(cur.out_edges) == 1 and self._chainable(cur.out_edges[0])):
                cur = self.nodes[cur.out_edges[0].target_id]
                chain.append(cur.transformation)
                chained_into[cur.id] = head.id
            vertices[head.id] = PlanVertex(
                id=head.id,
                name="->".join(t.name for t in chain),
                chain=chain,
                parallelism=head.parallelism,
                max_parallelism=head.max_parallelism,
                is_source=head.transformation.is_source,
            )
        # cross-chain edges
        for head_id, v in vertices.items():
            tail = self.nodes[chained_into_tail(self, head_id, chained_into)]
            for e in tail.out_edges:
                if chained_into.get(e.target_id) != head_id or e.target_id == head_id:
                    tgt_head = chained_into[e.target_id]
                    if tgt_head != head_id:
                        v.out_edges.append(StreamEdge(
                            head_id, tgt_head, e.partitioning, e.key_column,
                            input_index=e.input_index))
                        vertices[tgt_head].in_degree += 1
        return ExecutionPlan(list(vertices.values()), self.job_name)


def chained_into_tail(graph: StreamGraph, head_id: int,
                      chained_into: Dict[int, int]) -> int:
    """Last node id of the chain starting at head_id."""
    cur = graph.nodes[head_id]
    while (len(cur.out_edges) == 1 and
           chained_into.get(cur.out_edges[0].target_id) == head_id):
        cur = graph.nodes[cur.out_edges[0].target_id]
    return cur.id


@dataclass
class ExecutionPlan:
    """Topologically ordered vertices + routed edges — what executors run.

    The analog of the reference's ``JobGraph`` (operator chains as job
    vertices, edges with ship strategies).
    """

    vertices: List[PlanVertex]
    job_name: str = "job"

    def __post_init__(self):
        self.vertices = self._topo_sort(self.vertices)
        self.by_id = {v.id: v for v in self.vertices}
        for i, v in enumerate(self.vertices):
            v.topo_index = i

    @staticmethod
    def _topo_sort(vertices: List[PlanVertex]) -> List[PlanVertex]:
        indeg = {v.id: v.in_degree for v in vertices}
        by_id = {v.id: v for v in vertices}
        ready = sorted([v.id for v in vertices if indeg[v.id] == 0])
        order: List[PlanVertex] = []
        while ready:
            vid = ready.pop(0)
            order.append(by_id[vid])
            for e in by_id[vid].out_edges:
                indeg[e.target_id] -= 1
                if indeg[e.target_id] == 0:
                    ready.append(e.target_id)
        if len(order) != len(vertices):
            raise ValueError("cycle in execution plan")
        return order

    @property
    def sources(self) -> List[PlanVertex]:
        return [v for v in self.vertices if v.is_source]
