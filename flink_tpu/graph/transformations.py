"""Transformation DAG — the client-side program representation.

Analog of the reference's ``Transformation`` tree that the DataStream API
builds and ``StreamGraphGenerator.java:122`` consumes: every fluent API call
appends a node describing *what* to run (an operator factory) and *how* its
input arrives (a partitioning strategy).  Kept deliberately small: operators
are already batched, so a transformation is (id, name, operator-factory,
parallelism, inputs, partitioning).
"""

from __future__ import annotations

import itertools
from dataclasses import dataclass, field
from typing import Any, Callable, List, Optional

_ids = itertools.count(1)


class Partitioning:
    """Inter-operator exchange strategies (``runtime/partitioner/`` analog)."""

    FORWARD = "forward"        # same subtask, chainable
    HASH = "hash"              # keyBy: route by key group (KeyGroupStreamPartitioner)
    REBALANCE = "rebalance"    # round-robin
    RESCALE = "rescale"        # local round-robin
    BROADCAST = "broadcast"    # replicate to all
    GLOBAL = "global"          # everything to subtask 0
    SHUFFLE = "shuffle"        # random


@dataclass
class Transformation:
    """One node of the program DAG.

    operator_factory: () -> StreamOperator — a fresh operator per subtask.
    key_column:       set on keyed transformations (hash partitioning input).
    """

    name: str
    operator_factory: Optional[Callable[[], Any]]
    inputs: List["Transformation"] = field(default_factory=list)
    partitioning: str = Partitioning.FORWARD
    parallelism: Optional[int] = None
    max_parallelism: Optional[int] = None
    key_column: Optional[str] = None
    is_source: bool = False
    is_sink: bool = False
    source: Any = None           # Source instance for source transformations
    chainable: bool = True
    slot_sharing_group: str = "default"
    uid: Optional[str] = None    # stable operator id for savepoint mapping
    #: two-input transformations: per-input partitioning / key column
    #: overrides (None = use the single transformation-level values)
    input_partitionings: Optional[List[str]] = None
    input_key_columns: Optional[List[Optional[str]]] = None
    id: int = field(default_factory=lambda: next(_ids))

    def with_uid(self, uid: str) -> "Transformation":
        self.uid = uid
        return self

    def all_upstream(self) -> List["Transformation"]:
        """This node + every transitive input, deduped, any order."""
        seen: dict[int, Transformation] = {}
        stack = [self]
        while stack:
            t = stack.pop()
            if t.id in seen:
                continue
            seen[t.id] = t
            stack.extend(t.inputs)
        return list(seen.values())
