from flink_tpu.graph_lib.graph import Graph

__all__ = ["Graph"]
