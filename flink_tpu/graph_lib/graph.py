"""Graph library — the Gelly analog, TPU-native.

The reference's Gelly (``flink-libraries/flink-gelly``, ~60k LoC of
DataSet-based graph algorithms + iteration abstractions) re-designed as
dense array programs: a graph is (num_vertices, edge src[int32], edge
dst[int32], optional edge weights), algorithms are ``jax.ops.segment_sum``
message passing inside jitted supersteps — the scatter-gather /
vertex-centric model (``spargel``) IS one segment-sum per superstep on TPU.

Algorithms: PageRank, connected components (label propagation), SSSP
(Bellman-Ford style relaxation), triangle count, degrees, plus the generic
``scatter_gather`` harness the rest are built on.  Interop with the DataSet
API both ways (``from_dataset`` / ``as_dataset``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


class Graph:
    def __init__(self, num_vertices: int, src: np.ndarray, dst: np.ndarray,
                 weights: Optional[np.ndarray] = None):
        self.n = int(num_vertices)
        self.src = jnp.asarray(src, jnp.int32)
        self.dst = jnp.asarray(dst, jnp.int32)
        self.weights = (jnp.asarray(weights, jnp.float32)
                        if weights is not None else None)

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_edges(edges, num_vertices: Optional[int] = None,
                   weights=None) -> "Graph":
        e = np.asarray(edges, np.int64).reshape(-1, 2)
        n = num_vertices if num_vertices is not None else (int(e.max()) + 1
                                                           if e.size else 0)
        return Graph(n, e[:, 0], e[:, 1], weights)

    @staticmethod
    def from_dataset(ds, src_column: str = "src", dst_column: str = "dst",
                     weight_column: Optional[str] = None,
                     num_vertices: Optional[int] = None) -> "Graph":
        b = ds.collect_batch()
        src = np.asarray(b.column(src_column))
        dst = np.asarray(b.column(dst_column))
        n = num_vertices if num_vertices is not None else (
            int(max(src.max(), dst.max())) + 1 if len(b) else 0)
        w = np.asarray(b.column(weight_column)) if weight_column else None
        return Graph(n, src, dst, w)

    def as_dataset(self):
        from flink_tpu.dataset import ExecutionEnvironment
        env = ExecutionEnvironment()
        cols = {"src": np.asarray(self.src), "dst": np.asarray(self.dst)}
        if self.weights is not None:
            cols["weight"] = np.asarray(self.weights)
        return env.from_columns(cols)

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    def undirected(self) -> "Graph":
        """Add reverse edges (``Graph.getUndirected``)."""
        return Graph(self.n,
                     jnp.concatenate([self.src, self.dst]),
                     jnp.concatenate([self.dst, self.src]),
                     None if self.weights is None
                     else jnp.concatenate([self.weights, self.weights]))

    # -- degrees -------------------------------------------------------------
    def out_degrees(self) -> np.ndarray:
        return np.asarray(jax.ops.segment_sum(
            jnp.ones_like(self.src, jnp.int32), self.src, self.n))

    def in_degrees(self) -> np.ndarray:
        return np.asarray(jax.ops.segment_sum(
            jnp.ones_like(self.dst, jnp.int32), self.dst, self.n))

    # -- generic scatter-gather (vertex-centric supersteps) ------------------
    def scatter_gather(self, initial_values: np.ndarray,
                       message_fn: Callable,
                       combine: str,
                       update_fn: Callable,
                       max_supersteps: int,
                       converged: Optional[Callable] = None) -> np.ndarray:
        """Vertex-centric iteration (``ScatterGatherIteration`` analog).

        Per superstep (one jitted step): ``msgs = message_fn(values[src],
        weights)`` scattered to dst with ``combine`` (sum/min/max), then
        ``values' = update_fn(values, combined)``. Stops at
        ``max_supersteps`` or when ``converged(old, new)`` is True.
        """
        seg = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
               "max": jax.ops.segment_max}[combine]

        @jax.jit
        def superstep(values):
            msgs = message_fn(values[self.src], self.weights)
            combined = seg(msgs, self.dst, self.n)
            return update_fn(values, combined)

        values = jnp.asarray(initial_values)
        for _ in range(max_supersteps):
            new = superstep(values)
            if converged is not None and bool(converged(values, new)):
                values = new
                break
            values = new
        return np.asarray(values)

    # -- algorithms ----------------------------------------------------------
    def pagerank(self, damping: float = 0.85, num_iterations: int = 30,
                 tol: float = 0.0) -> np.ndarray:
        """Power iteration with dangling-mass redistribution (``PageRank``)."""
        n = self.n
        out_deg = jnp.asarray(self.out_degrees(), jnp.float32)
        dangling = out_deg == 0
        safe_deg = jnp.where(dangling, 1.0, out_deg)

        @jax.jit
        def step(ranks):
            contrib = ranks / safe_deg
            spread = jax.ops.segment_sum(contrib[self.src], self.dst, n)
            dangling_mass = jnp.sum(jnp.where(dangling, ranks, 0.0))
            return ((1.0 - damping) / n
                    + damping * (spread + dangling_mass / n))

        ranks = jnp.full(n, 1.0 / n, jnp.float32)
        for _ in range(num_iterations):
            new = step(ranks)
            if tol and float(jnp.abs(new - ranks).sum()) < tol:
                ranks = new
                break
            ranks = new
        return np.asarray(ranks)

    def connected_components(self, max_supersteps: int = 0) -> np.ndarray:
        """Min-label propagation over the undirected graph
        (``ConnectedComponents`` delta-iteration analog)."""
        g = self.undirected()
        steps = max_supersteps or self.n

        def msg(vals, _w):
            return vals

        def update(vals, combined):
            return jnp.minimum(vals, combined)

        return g.scatter_gather(
            jnp.arange(self.n, dtype=jnp.int32), msg, "min", update, steps,
            converged=lambda a, b: bool(jnp.array_equal(a, b)))

    def sssp(self, source: int, num_iterations: int = 0) -> np.ndarray:
        """Single-source shortest paths (``SingleSourceShortestPaths``):
        Bellman-Ford relaxation, one segment_min per superstep."""
        inf = jnp.float32(jnp.inf)
        w = (self.weights if self.weights is not None
             else jnp.ones_like(self.src, jnp.float32))
        dist0 = jnp.full(self.n, inf, jnp.float32).at[source].set(0.0)
        steps = num_iterations or self.n

        def msg(vals, weights):
            return vals + weights

        def update(vals, combined):
            return jnp.minimum(vals, combined)

        def message_fn(src_vals, weights):
            return msg(src_vals, w)

        return self.scatter_gather(
            dist0, message_fn, "min", update, steps,
            converged=lambda a, b: bool(jnp.array_equal(a, b)))

    def triangle_count(self) -> int:
        """Total triangles (``TriangleEnumerator`` analog): dense adjacency
        trace(A^3)/6 for small graphs, neighbor-set intersection otherwise."""
        n = self.n
        if n <= 2048:
            # float64 on host: a float32 MXU trace loses exactness past
            # 2^24 triangles; counts must be exact
            a = np.zeros((n, n), np.float64)
            src_np, dst_np = np.asarray(self.src), np.asarray(self.dst)
            a[src_np, dst_np] = 1.0
            a[dst_np, src_np] = 1.0
            np.fill_diagonal(a, 0.0)  # drop self loops
            t = np.trace(a @ a @ a)
            return int(round(t / 6.0))
        # host fallback: sorted adjacency intersection
        src = np.asarray(self.src)
        dst = np.asarray(self.dst)
        adj = {}
        for s, d in zip(src.tolist(), dst.tolist()):
            if s == d:
                continue
            adj.setdefault(s, set()).add(d)
            adj.setdefault(d, set()).add(s)
        count = 0
        for v, nbrs in adj.items():
            for u in nbrs:
                if u > v:
                    count += len(nbrs & adj.get(u, set())
                                 & {x for x in adj.get(u, set()) if x > u})
        return count

    def k_core(self, k: int, max_iterations: int = 0) -> np.ndarray:
        """bool[n] membership in the k-core (``KCore`` analog): iteratively
        peel vertices with degree < k — vectorized per round.  Degree is
        over DISTINCT neighbors (duplicate and already-bidirectional edge
        lists dedup first, matching triangle_count/clustering semantics)."""
        src0, dst0 = np.asarray(self.src), np.asarray(self.dst)
        keep = src0 != dst0
        lo = np.minimum(src0[keep], dst0[keep]).astype(np.int64)
        hi = np.maximum(src0[keep], dst0[keep]).astype(np.int64)
        uniq = np.unique(lo * np.int64(self.n) + hi)
        src = np.concatenate([uniq // self.n, uniq % self.n]).astype(np.int64)
        dst = np.concatenate([uniq % self.n, uniq // self.n]).astype(np.int64)
        alive = np.ones(self.n, bool)
        limit = max_iterations or self.n
        for _ in range(limit):
            live_edge = alive[src] & alive[dst]
            deg = np.bincount(dst[live_edge], minlength=self.n)
            nxt = alive & (deg >= k)
            if (nxt == alive).all():
                break
            alive = nxt
        return alive

    def clustering_coefficient(self) -> np.ndarray:
        """float[n] local clustering coefficient (``LocalClusteringCoefficient``
        analog): triangles through v / (deg(v) choose 2)."""
        g = self.undirected()
        src, dst = np.asarray(g.src), np.asarray(g.dst)
        keep = src != dst
        src, dst = src[keep], dst[keep]
        adj: dict = {}
        for s, d in zip(src.tolist(), dst.tolist()):
            adj.setdefault(s, set()).add(d)
        tri = np.zeros(g.n, np.int64)
        for v, nbrs in adj.items():
            t = 0
            for u in nbrs:
                t += len(nbrs & adj.get(u, set()))
            tri[v] = t // 2
        deg = np.asarray([len(adj.get(v, ())) for v in range(g.n)])
        denom = deg * (deg - 1) / 2
        with np.errstate(divide="ignore", invalid="ignore"):
            cc = np.where(denom > 0, tri / np.maximum(denom, 1), 0.0)
        return cc

    def bfs_levels(self, sources: "np.ndarray | int",
                   max_supersteps: int = 0,
                   directed: bool = False) -> np.ndarray:
        """int32[n] hop distance from the nearest source (multi-source BFS);
        unreachable = -1.  Default treats edges as undirected;
        ``directed=True`` follows edge direction only (matching ``sssp``,
        which always runs on the directed edges)."""
        srcs = np.atleast_1d(np.asarray(sources, np.int64))
        inf = np.iinfo(np.int32).max
        init = np.full(self.n, inf, np.int32)
        init[srcs] = 0

        def msg(vals, _w):
            return jnp.where(vals < inf, vals + 1, inf)

        def update(vals, combined):
            return jnp.minimum(vals, combined).astype(jnp.int32)

        g = self if directed else self.undirected()
        out = g.scatter_gather(
            init, msg, "min", update, max_supersteps or self.n,
            converged=lambda a, b: bool(jnp.array_equal(a, b)))
        return np.where(out >= inf, -1, out).astype(np.int32)

    def label_propagation(self, initial_labels: np.ndarray,
                          num_iterations: int = 10) -> np.ndarray:
        """Community detection by iterated max-label adoption
        (``LabelPropagation`` analog, deterministic max tie-break)."""
        g = self.undirected()

        def msg(vals, _w):
            return vals

        def update(vals, combined):
            # adopt the max neighbor label (0 in-degree keeps its own)
            has_nb = combined > jnp.iinfo(jnp.int32).min
            return jnp.where(has_nb, jnp.maximum(vals, combined), vals)

        return g.scatter_gather(
            jnp.asarray(initial_labels, jnp.int32), msg, "max", update,
            num_iterations,
            converged=lambda a, b: bool(jnp.array_equal(a, b)))
