"""Graph library — the Gelly analog, TPU-native.

The reference's Gelly (``flink-libraries/flink-gelly``, ~60k LoC of
DataSet-based graph algorithms + iteration abstractions) re-designed as
dense array programs: a graph is (num_vertices, edge src[int32], edge
dst[int32], optional edge weights), algorithms are ``jax.ops.segment_sum``
message passing inside jitted supersteps — the scatter-gather /
vertex-centric model (``spargel``) IS one segment-sum per superstep on TPU.

Algorithms (the ``flink-gelly`` ``library/`` roster): PageRank, connected
components, SSSP (Bellman-Ford relaxation), triangle count, k-core, local
clustering coefficient, BFS levels, label propagation, HITS, per-edge
Jaccard similarity and Adamic-Adar, structural summarization (contract by
label), bipartite projections, aggregate vertex metrics — plus the
generic ``scatter_gather`` harness the rest are built on.  ``scatter_gather``/``pagerank`` take a ``mesh`` to run
EDGE-SHARDED over a device mesh (shard_map segment-combine per device, one
``psum``/``pmin``/``pmax`` over ICI per superstep).  Interop with the
DataSet API both ways (``from_dataset`` / ``as_dataset``).
"""

from __future__ import annotations

from functools import partial
from typing import Callable, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


#: combine kind -> segment op (single source of truth for both the
#: single-device and mesh supersteps)
_SEGMENT_OPS = {"sum": jax.ops.segment_sum, "min": jax.ops.segment_min,
                "max": jax.ops.segment_max}


class Graph:
    def __init__(self, num_vertices: int, src: np.ndarray, dst: np.ndarray,
                 weights: Optional[np.ndarray] = None):
        self.n = int(num_vertices)
        self.src = jnp.asarray(src, jnp.int32)
        self.dst = jnp.asarray(dst, jnp.int32)
        self.weights = (jnp.asarray(weights, jnp.float32)
                        if weights is not None else None)

    # -- construction --------------------------------------------------------
    @staticmethod
    def from_edges(edges, num_vertices: Optional[int] = None,
                   weights=None) -> "Graph":
        e = np.asarray(edges, np.int64).reshape(-1, 2)
        n = num_vertices if num_vertices is not None else (int(e.max()) + 1
                                                           if e.size else 0)
        return Graph(n, e[:, 0], e[:, 1], weights)

    @staticmethod
    def from_dataset(ds, src_column: str = "src", dst_column: str = "dst",
                     weight_column: Optional[str] = None,
                     num_vertices: Optional[int] = None) -> "Graph":
        b = ds.collect_batch()
        src = np.asarray(b.column(src_column))
        dst = np.asarray(b.column(dst_column))
        n = num_vertices if num_vertices is not None else (
            int(max(src.max(), dst.max())) + 1 if len(b) else 0)
        w = np.asarray(b.column(weight_column)) if weight_column else None
        return Graph(n, src, dst, w)

    def as_dataset(self):
        from flink_tpu.dataset import ExecutionEnvironment
        env = ExecutionEnvironment()
        cols = {"src": np.asarray(self.src), "dst": np.asarray(self.dst)}
        if self.weights is not None:
            cols["weight"] = np.asarray(self.weights)
        return env.from_columns(cols)

    @property
    def num_edges(self) -> int:
        return int(self.src.shape[0])

    def undirected(self) -> "Graph":
        """Add reverse edges (``Graph.getUndirected``)."""
        return Graph(self.n,
                     jnp.concatenate([self.src, self.dst]),
                     jnp.concatenate([self.dst, self.src]),
                     None if self.weights is None
                     else jnp.concatenate([self.weights, self.weights]))

    # -- degrees -------------------------------------------------------------
    def out_degrees(self) -> np.ndarray:
        return np.asarray(jax.ops.segment_sum(
            jnp.ones_like(self.src, jnp.int32), self.src, self.n))

    def in_degrees(self) -> np.ndarray:
        return np.asarray(jax.ops.segment_sum(
            jnp.ones_like(self.dst, jnp.int32), self.dst, self.n))

    # -- generic scatter-gather (vertex-centric supersteps) ------------------
    def scatter_gather(self, initial_values: np.ndarray,
                       message_fn: Callable,
                       combine: str,
                       update_fn: Callable,
                       max_supersteps: int,
                       converged: Optional[Callable] = None,
                       mesh=None) -> np.ndarray:
        """Vertex-centric iteration (``ScatterGatherIteration`` analog).

        Per superstep (one jitted step): ``msgs = message_fn(values[src],
        weights)`` scattered to dst with ``combine`` (sum/min/max), then
        ``values' = update_fn(values, combined)``. Stops at
        ``max_supersteps`` or when ``converged(old, new)`` is True.

        ``mesh``: a ``jax.sharding.Mesh`` — EDGES shard across devices
        (the natural SPMD cut for message passing), vertex values
        replicate; each device segment-combines its local messages and the
        partials merge with one collective per superstep (``psum`` /
        ``pmin`` / ``pmax`` over ICI).  Combine identities pad the edge
        list to a device-divisible length."""
        if mesh is None:
            seg = _SEGMENT_OPS[combine]

            @jax.jit
            def superstep(values):
                msgs = message_fn(values[self.src], self.weights)
                combined = seg(msgs, self.dst, self.n)
                return update_fn(values, combined)
        else:
            superstep = self._mesh_superstep(mesh, message_fn, combine,
                                             update_fn)

        values = jnp.asarray(initial_values)
        for _ in range(max_supersteps):
            new = superstep(values)
            if converged is not None and bool(converged(values, new)):
                values = new
                break
            values = new
        return np.asarray(values)

    def _mesh_superstep(self, mesh, message_fn: Callable, combine: str,
                        update_fn: Callable):
        """Edge-sharded superstep: pad edges to D-divisible, shard_map the
        local segment-combine, merge partials with the matching collective."""
        from flink_tpu.parallel.mesh import shard_map_compat
        from jax.sharding import NamedSharding, PartitionSpec as P

        D = mesh.devices.size
        axis = mesh.axis_names[0]
        E = self.src.shape[0]
        Ep = -(-max(E, 1) // D) * D
        # padding rows scatter the combine's identity to vertex 0
        pad_src = jnp.zeros(Ep - E, jnp.int32)
        pad_dst = jnp.zeros(Ep - E, jnp.int32)
        src_p = jnp.concatenate([self.src, pad_src])
        dst_p = jnp.concatenate([self.dst, pad_dst])
        w = self.weights
        if w is not None:
            w = jnp.concatenate([w, jnp.zeros(Ep - E, w.dtype)])
        valid = jnp.concatenate([jnp.ones(E, bool), jnp.zeros(Ep - E, bool)])
        seg = _SEGMENT_OPS[combine]
        coll = {"sum": jax.lax.psum, "min": jax.lax.pmin,
                "max": jax.lax.pmax}[combine]

        def ident_of(dtype):
            if combine == "sum":
                return jnp.zeros((), dtype)
            if jnp.issubdtype(dtype, jnp.integer):
                info = jnp.iinfo(dtype)
                return jnp.asarray(info.max if combine == "min"
                                   else info.min, dtype)
            return jnp.asarray(jnp.inf if combine == "min" else -jnp.inf,
                               dtype)

        n = self.n
        espec = P(axis)
        shard = NamedSharding(mesh, espec)
        src_p = jax.device_put(src_p, shard)
        dst_p = jax.device_put(dst_p, shard)
        valid = jax.device_put(valid, shard)
        if w is not None:
            w = jax.device_put(w, shard)

        in_specs = (P(), espec, espec, espec) + ((espec,) if w is not None
                                                 else ())

        @partial(shard_map_compat, mesh=mesh, in_specs=in_specs,
                 out_specs=P())
        def local_combine(values, src_l, dst_l, valid_l, *w_l):
            msgs = message_fn(values[src_l], w_l[0] if w_l else None)
            # broadcast the edge mask over any trailing value dims (vector
            # vertex values must behave exactly like the single-device path)
            mask = valid_l.reshape(valid_l.shape + (1,) * (msgs.ndim - 1))
            msgs = jnp.where(mask, msgs, ident_of(msgs.dtype))
            part = seg(msgs, dst_l, n)
            return coll(part, axis)

        @jax.jit
        def superstep(values):
            args = (values, src_p, dst_p, valid) + ((w,) if w is not None
                                                    else ())
            combined = local_combine(*args)
            return update_fn(values, combined)

        return superstep

    # -- algorithms ----------------------------------------------------------
    def pagerank(self, damping: float = 0.85, num_iterations: int = 30,
                 tol: float = 0.0, mesh=None) -> np.ndarray:
        """Power iteration with dangling-mass redistribution (``PageRank``).

        ``mesh``: run edge-sharded over a device mesh — per-edge
        contributions carry 1/out_degree as edge weights, each device
        segment-sums its shard, partials ``psum`` over ICI, and the
        dangling-mass/teleport update runs on the replicated rank vector."""
        n = self.n
        out_deg = jnp.asarray(self.out_degrees(), jnp.float32)
        dangling = out_deg == 0
        safe_deg = jnp.where(dangling, 1.0, out_deg)
        if mesh is not None:
            inv_deg_e = (1.0 / np.asarray(safe_deg))[np.asarray(self.src)]
            g = Graph(n, self.src, self.dst, inv_deg_e)

            def msg(vals, w):
                return vals * w

            def update(ranks, spread):
                dm = jnp.sum(jnp.where(dangling, ranks, 0.0))
                return (1.0 - damping) / n + damping * (spread + dm / n)

            conv = ((lambda a, b: bool(jnp.abs(b - a).sum() < tol))
                    if tol else None)
            return g.scatter_gather(
                jnp.full(n, 1.0 / n, jnp.float32), msg, "sum", update,
                num_iterations, conv, mesh=mesh)

        @jax.jit
        def step(ranks):
            contrib = ranks / safe_deg
            spread = jax.ops.segment_sum(contrib[self.src], self.dst, n)
            dangling_mass = jnp.sum(jnp.where(dangling, ranks, 0.0))
            return ((1.0 - damping) / n
                    + damping * (spread + dangling_mass / n))

        ranks = jnp.full(n, 1.0 / n, jnp.float32)
        for _ in range(num_iterations):
            new = step(ranks)
            if tol and float(jnp.abs(new - ranks).sum()) < tol:
                ranks = new
                break
            ranks = new
        return np.asarray(ranks)

    def connected_components(self, max_supersteps: int = 0) -> np.ndarray:
        """Min-label propagation over the undirected graph
        (``ConnectedComponents`` delta-iteration analog)."""
        g = self.undirected()
        steps = max_supersteps or self.n

        def msg(vals, _w):
            return vals

        def update(vals, combined):
            return jnp.minimum(vals, combined)

        return g.scatter_gather(
            jnp.arange(self.n, dtype=jnp.int32), msg, "min", update, steps,
            converged=lambda a, b: bool(jnp.array_equal(a, b)))

    def sssp(self, source: int, num_iterations: int = 0) -> np.ndarray:
        """Single-source shortest paths (``SingleSourceShortestPaths``):
        Bellman-Ford relaxation, one segment_min per superstep."""
        inf = jnp.float32(jnp.inf)
        w = (self.weights if self.weights is not None
             else jnp.ones_like(self.src, jnp.float32))
        dist0 = jnp.full(self.n, inf, jnp.float32).at[source].set(0.0)
        steps = num_iterations or self.n

        def msg(vals, weights):
            return vals + weights

        def update(vals, combined):
            return jnp.minimum(vals, combined)

        def message_fn(src_vals, weights):
            return msg(src_vals, w)

        return self.scatter_gather(
            dist0, message_fn, "min", update, steps,
            converged=lambda a, b: bool(jnp.array_equal(a, b)))

    def triangle_count(self) -> int:
        """Total triangles (``TriangleEnumerator`` analog): dense adjacency
        trace(A^3)/6 for small graphs, neighbor-set intersection otherwise."""
        n = self.n
        if n <= 2048:
            # float64 on host: a float32 MXU trace loses exactness past
            # 2^24 triangles; counts must be exact
            a = np.zeros((n, n), np.float64)
            src_np, dst_np = np.asarray(self.src), np.asarray(self.dst)
            a[src_np, dst_np] = 1.0
            a[dst_np, src_np] = 1.0
            np.fill_diagonal(a, 0.0)  # drop self loops
            t = np.trace(a @ a @ a)
            return int(round(t / 6.0))
        # host fallback: sorted adjacency intersection
        src = np.asarray(self.src)
        dst = np.asarray(self.dst)
        adj = {}
        for s, d in zip(src.tolist(), dst.tolist()):
            if s == d:
                continue
            adj.setdefault(s, set()).add(d)
            adj.setdefault(d, set()).add(s)
        count = 0
        for v, nbrs in adj.items():
            for u in nbrs:
                if u > v:
                    count += len(nbrs & adj.get(u, set())
                                 & {x for x in adj.get(u, set()) if x > u})
        return count

    def k_core(self, k: int, max_iterations: int = 0) -> np.ndarray:
        """bool[n] membership in the k-core (``KCore`` analog): iteratively
        peel vertices with degree < k — vectorized per round.  Degree is
        over DISTINCT neighbors (duplicate and already-bidirectional edge
        lists dedup first, matching triangle_count/clustering semantics)."""
        src0, dst0 = np.asarray(self.src), np.asarray(self.dst)
        keep = src0 != dst0
        lo = np.minimum(src0[keep], dst0[keep]).astype(np.int64)
        hi = np.maximum(src0[keep], dst0[keep]).astype(np.int64)
        uniq = np.unique(lo * np.int64(self.n) + hi)
        src = np.concatenate([uniq // self.n, uniq % self.n]).astype(np.int64)
        dst = np.concatenate([uniq % self.n, uniq // self.n]).astype(np.int64)
        alive = np.ones(self.n, bool)
        limit = max_iterations or self.n
        for _ in range(limit):
            live_edge = alive[src] & alive[dst]
            deg = np.bincount(dst[live_edge], minlength=self.n)
            nxt = alive & (deg >= k)
            if (nxt == alive).all():
                break
            alive = nxt
        return alive

    def clustering_coefficient(self) -> np.ndarray:
        """float[n] local clustering coefficient (``LocalClusteringCoefficient``
        analog): triangles through v / (deg(v) choose 2)."""
        g = self.undirected()
        src, dst = np.asarray(g.src), np.asarray(g.dst)
        keep = src != dst
        src, dst = src[keep], dst[keep]
        adj: dict = {}
        for s, d in zip(src.tolist(), dst.tolist()):
            adj.setdefault(s, set()).add(d)
        tri = np.zeros(g.n, np.int64)
        for v, nbrs in adj.items():
            t = 0
            for u in nbrs:
                t += len(nbrs & adj.get(u, set()))
            tri[v] = t // 2
        deg = np.asarray([len(adj.get(v, ())) for v in range(g.n)])
        denom = deg * (deg - 1) / 2
        with np.errstate(divide="ignore", invalid="ignore"):
            cc = np.where(denom > 0, tri / np.maximum(denom, 1), 0.0)
        return cc

    _BFS_INF = np.iinfo(np.int32).max

    def _bfs_propagate(self, init: np.ndarray, directed: bool,
                       max_supersteps: int, mesh=None) -> np.ndarray:
        """Shared BFS superstep (min-combine hop propagation) over any
        init shape — [n] for ``bfs_levels``, [n, n] for the simultaneous
        all-pairs variant; -1 marks unreachable."""
        inf = self._BFS_INF

        def msg(vals, _w):
            return jnp.where(vals < inf, vals + 1, inf)

        def update(vals, combined):
            return jnp.minimum(vals, combined).astype(jnp.int32)

        g = self if directed else self.undirected()
        out = g.scatter_gather(
            init, msg, "min", update, max_supersteps or self.n,
            converged=lambda a, b: bool(jnp.array_equal(a, b)), mesh=mesh)
        return np.where(out >= inf, -1, out).astype(np.int32)

    def bfs_levels(self, sources: "np.ndarray | int",
                   max_supersteps: int = 0,
                   directed: bool = False, mesh=None) -> np.ndarray:
        """int32[n] hop distance from the nearest source (multi-source BFS);
        unreachable = -1.  Default treats edges as undirected;
        ``directed=True`` follows edge direction only (matching ``sssp``,
        which always runs on the directed edges)."""
        srcs = np.atleast_1d(np.asarray(sources, np.int64))
        init = np.full(self.n, self._BFS_INF, np.int32)
        init[srcs] = 0
        return self._bfs_propagate(init, directed, max_supersteps, mesh)

    def label_propagation(self, initial_labels: np.ndarray,
                          num_iterations: int = 10) -> np.ndarray:
        """Community detection by iterated max-label adoption
        (``LabelPropagation`` analog, deterministic max tie-break)."""
        g = self.undirected()

        def msg(vals, _w):
            return vals

        def update(vals, combined):
            # adopt the max neighbor label (0 in-degree keeps its own)
            has_nb = combined > jnp.iinfo(jnp.int32).min
            return jnp.where(has_nb, jnp.maximum(vals, combined), vals)

        return g.scatter_gather(
            jnp.asarray(initial_labels, jnp.int32), msg, "max", update,
            num_iterations,
            converged=lambda a, b: bool(jnp.array_equal(a, b)))

    def hits(self, num_iterations: int = 20
             ) -> Tuple[np.ndarray, np.ndarray]:
        """-> (hubs, authorities), L2-normalized (``HITS`` analog): one
        jitted step does both segment-sums per iteration."""
        n = self.n

        @jax.jit
        def step(hub):
            auth = jax.ops.segment_sum(hub[self.src], self.dst, n)
            auth = auth / jnp.maximum(jnp.linalg.norm(auth), 1e-12)
            hub2 = jax.ops.segment_sum(auth[self.dst], self.src, n)
            return hub2 / jnp.maximum(jnp.linalg.norm(hub2), 1e-12), auth

        hub = jnp.ones(n, jnp.float32) / jnp.sqrt(jnp.maximum(n, 1))
        auth = hub
        for _ in range(num_iterations):
            hub, auth = step(hub)
        return np.asarray(hub), np.asarray(auth)

    # one source of truth for the similarity kernels' neighborhood views:
    # the dense/sparse split, symmetrization, and self-loop policy must
    # stay identical across jaccard_similarity / adamic_adar
    _DENSE_LIMIT = 4096

    def _dense_undirected_adjacency(self) -> np.ndarray:
        """Symmetric 0/1 adjacency with a zero diagonal (n <= _DENSE_LIMIT
        — the MXU-native matmul representation)."""
        a = np.zeros((self.n, self.n), np.float32)
        a[np.asarray(self.src), np.asarray(self.dst)] = 1.0
        a[np.asarray(self.dst), np.asarray(self.src)] = 1.0
        np.fill_diagonal(a, 0.0)
        return a

    def _undirected_neighbor_sets(self) -> dict:
        """vertex -> set of neighbors (self-loops dropped) — the sparse
        twin of :meth:`_dense_undirected_adjacency`."""
        adj: dict = {}
        for s, d in zip(np.asarray(self.src).tolist(),
                        np.asarray(self.dst).tolist()):
            if s != d:
                adj.setdefault(s, set()).add(d)
                adj.setdefault(d, set()).add(s)
        return adj

    def adamic_adar(self) -> np.ndarray:
        """Per-EDGE Adamic-Adar index: sum over common neighbors w of
        ``1 / log(deg(w))`` (``AdamicAdar.java`` in Gelly's similarity
        library).  Dense path: ``A @ diag(1/log deg) @ A.T`` — two
        MXU-native matmuls; sorted-set fallback beyond 4096 vertices."""
        src_np = np.asarray(self.src)
        dst_np = np.asarray(self.dst)
        if self.n <= self._DENSE_LIMIT:
            a = self._dense_undirected_adjacency()
            deg = a.sum(axis=1)
            inv_log = np.where(deg > 1, 1.0 / np.log(np.maximum(deg, 2.0)),
                               0.0).astype(np.float32)
            aj = jnp.asarray(a)
            scores = np.asarray((aj * jnp.asarray(inv_log)[None, :]) @ aj.T)
            return scores[src_np, dst_np]
        adj = self._undirected_neighbor_sets()
        out = np.zeros(len(src_np), np.float32)
        for i, (s, d) in enumerate(zip(src_np.tolist(), dst_np.tolist())):
            commons = adj.get(s, set()) & adj.get(d, set())
            out[i] = sum(1.0 / np.log(len(adj[w]))
                         for w in commons if len(adj[w]) > 1)
        return out

    def summarize(self, vertex_labels: np.ndarray
                  ) -> Tuple["Graph", np.ndarray, np.ndarray]:
        """Structural summarization (``Summarization.java``): contract
        vertices sharing a label into one summary vertex; summary edges
        are the DISTINCT (src-label, dst-label) pairs weighted by how many
        original edges they group.  Returns ``(summary graph with edge
        counts as weights, label of each summary vertex, original-vertex
        count per summary vertex)``."""
        labels = np.asarray(vertex_labels)
        uniq, inv = np.unique(labels, return_inverse=True)
        group_sizes = np.bincount(inv, minlength=len(uniq))
        s = inv[np.asarray(self.src)]
        d = inv[np.asarray(self.dst)]
        pair = s.astype(np.int64) * len(uniq) + d
        upair, counts = np.unique(pair, return_counts=True)
        g = Graph(len(uniq), upair // len(uniq), upair % len(uniq),
                  counts.astype(np.float32))
        return g, uniq, group_sizes.astype(np.int64)

    def bipartite_projection(self, left_size: int,
                             onto_left: bool = True) -> "Graph":
        """Bipartite projection (Gelly's ``BipartiteGraph``
        ``projectionTopSimple`` analog): edges run left->right with left
        ids in ``[0, left_size)`` and right ids in ``[left_size, n)``;
        the projection connects two LEFT vertices whenever they share a
        right neighbor (or two right vertices, ``onto_left=False``),
        weighted by the number of shared neighbors.  Self-loops drop."""
        src_np = np.asarray(self.src)
        dst_np = np.asarray(self.dst)
        if onto_left:
            keys, others, size = dst_np - left_size, src_np, left_size
        else:
            keys, others, size = src_np, dst_np - left_size, self.n - left_size
        nkeys = (self.n - left_size) if onto_left else left_size
        if size <= self._DENSE_LIMIT and nkeys <= self._DENSE_LIMIT:
            # shared-neighbor counts = B.T @ B on the biadjacency matrix —
            # the same MXU-native kernel as the similarity methods; strict
            # upper triangle keeps (u < v) pairs once, no self-loops
            b = np.zeros((nkeys, size), np.float32)
            b[keys, others] = 1.0
            counts = np.asarray(jnp.asarray(b).T @ jnp.asarray(b))
            es, ed = np.nonzero(np.triu(counts, k=1))
            return Graph(size, es.astype(np.int64), ed.astype(np.int64),
                         counts[es, ed].astype(np.float32))
        pairs: dict = {}
        by_key: dict = {}
        for k, v in zip(keys.tolist(), others.tolist()):
            by_key.setdefault(k, []).append(v)
        for members in by_key.values():
            ms = sorted(set(members))
            for i, u in enumerate(ms):
                for v in ms[i + 1:]:
                    pairs[(u, v)] = pairs.get((u, v), 0) + 1
        if not pairs:
            return Graph(size, np.empty(0, np.int64), np.empty(0, np.int64),
                         np.empty(0, np.float32))
        es = np.asarray([p[0] for p in pairs], np.int64)
        ed = np.asarray([p[1] for p in pairs], np.int64)
        w = np.asarray(list(pairs.values()), np.float32)
        return Graph(size, es, ed, w)

    def vertex_metrics(self) -> dict:
        """Aggregate graph metrics (``VertexMetrics.java``): vertex/edge
        counts, average degree, max degree, and the number of vertices
        with at least one edge."""
        deg = self.out_degrees() + self.in_degrees()
        return {
            "vertices": self.n,
            "edges": self.num_edges,
            "average_degree": float(deg.mean()) if self.n else 0.0,
            "max_degree": int(deg.max()) if self.n else 0,
            "vertices_with_edges": int((deg > 0).sum()),
        }

    def all_pairs_distances(self, directed: bool = False,
                            max_supersteps: int = 0,
                            mesh=None) -> np.ndarray:
        """int32[n, n] hop distances (``d[i, j]`` = hops from i to j,
        -1 = unreachable) — ALL sources propagate simultaneously as one
        [n, n] vertex-value matrix through the same scatter-gather
        superstep (one segment-min per step instead of n BFS runs; the
        TPU-native cut for the all-pairs family).  n² memory: sized for
        the analysis-scale graphs the eccentricity/closeness family
        targets."""
        init = np.full((self.n, self.n), self._BFS_INF, np.int32)
        np.fill_diagonal(init, 0)
        out = self._bfs_propagate(init, directed, max_supersteps, mesh)
        # out[i, j] = distance from column-source j; expose row-source
        # orientation d[i, j] = i -> j
        return out.T.copy()

    def eccentricity(self, mesh=None,
                     distances: Optional[np.ndarray] = None) -> np.ndarray:
        """int32[n] eccentricity: each vertex's maximum hop distance to
        any REACHABLE vertex over the undirected graph (isolated
        vertices: 0) — the ``Eccentricity`` library analog.  Pass a
        precomputed ``all_pairs_distances()`` matrix to share one BFS
        across the eccentricity/closeness/diameter family."""
        d = (distances if distances is not None
             else self.all_pairs_distances(mesh=mesh))
        masked = np.where(d >= 0, d, 0)
        return masked.max(axis=1).astype(np.int32)

    def closeness_centrality(self, mesh=None,
                             distances: Optional[np.ndarray] = None
                             ) -> np.ndarray:
        """float32[n] closeness with the Wasserman–Faust component
        correction: ``((r-1)/(n-1)) * ((r-1)/sum_d)`` where r = reachable
        vertices (incl. self) — comparable across disconnected
        components; isolated vertices score 0."""
        d = (distances if distances is not None
             else self.all_pairs_distances(mesh=mesh))
        reach = (d >= 0).sum(axis=1)                  # includes self (d=0)
        dist_sum = np.where(d > 0, d, 0).sum(axis=1)
        r1 = (reach - 1).astype(np.float64)
        denom = np.maximum(dist_sum, 1)
        frac = np.where(dist_sum > 0, r1 / denom, 0.0)
        scale = r1 / max(self.n - 1, 1)
        return (scale * frac).astype(np.float32)

    def diameter_radius(self, mesh=None,
                        distances: Optional[np.ndarray] = None) -> dict:
        """Graph diameter/radius over the undirected graph's non-isolated
        vertices.  Self-loops do not make a vertex non-isolated (they
        contribute no path to anywhere else, like the triangle/k-core
        paths that drop them)."""
        ecc = self.eccentricity(mesh=mesh, distances=distances)
        src_np = np.asarray(self.src)
        dst_np = np.asarray(self.dst)
        real = src_np != dst_np                  # ignore self-loops
        deg = np.zeros(self.n, np.int64)
        np.add.at(deg, src_np[real], 1)
        np.add.at(deg, dst_np[real], 1)
        live = ecc[deg > 0]
        if live.size == 0:
            return {"diameter": 0, "radius": 0}
        return {"diameter": int(live.max()), "radius": int(live.min())}

    def jaccard_similarity(self) -> np.ndarray:
        """Per-EDGE Jaccard index |N(u) ∩ N(v)| / |N(u) ∪ N(v)| over the
        undirected neighborhood (``JaccardIndex`` analog).  Dense
        adjacency matmul (the MXU-native kernel) for n <= 4096; sorted
        set intersection beyond."""
        src_np = np.asarray(self.src)
        dst_np = np.asarray(self.dst)
        if self.n <= self._DENSE_LIMIT:
            a = self._dense_undirected_adjacency()
            common = np.asarray(
                jnp.asarray(a) @ jnp.asarray(a).T)[src_np, dst_np]
            deg = a.sum(axis=1)
            union = deg[src_np] + deg[dst_np] - common
            return np.where(union > 0, common / np.maximum(union, 1.0), 0.0)
        adj = self._undirected_neighbor_sets()
        out = np.zeros(len(src_np), np.float32)
        for i, (s, d) in enumerate(zip(src_np.tolist(), dst_np.tolist())):
            ns, nd = adj.get(s, set()), adj.get(d, set())
            inter = len(ns & nd)
            union = len(ns | nd)
            out[i] = inter / union if union else 0.0
        return out
