"""History server: archived finished jobs, served after the cluster is gone.

Analog of the reference's ``flink-runtime/.../history/`` (``HistoryServer``
+ ``FsJobArchivist``): when a job reaches a terminal state its REST-visible
facts (status, vertices, metrics, checkpoint counts) are archived as one
JSON document per job; a standalone :class:`HistoryServer` serves the
archive directory with the same ``/jobs`` shapes the live REST API uses, so
the dashboard/CLI work identically against finished clusters.
"""

from __future__ import annotations

import json
import os
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional


def archive_job(archive_dir: str, job_id: str,
                status: Dict[str, Any]) -> str:
    """Write one job's terminal REST document (``FsJobArchivist.archiveJob``
    analog); returns the archive path."""
    os.makedirs(archive_dir, exist_ok=True)
    doc = dict(status)
    doc.setdefault("id", job_id)
    doc["archived_at"] = int(time.time() * 1000)
    path = os.path.join(archive_dir, f"{job_id}.json")
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(doc, f, default=str)
    os.replace(tmp, path)
    return path


def list_archived(archive_dir: str) -> List[Dict[str, Any]]:
    out = []
    if not os.path.isdir(archive_dir):
        return out
    for fn in sorted(os.listdir(archive_dir)):
        if not fn.endswith(".json"):
            continue
        try:
            with open(os.path.join(archive_dir, fn)) as f:
                out.append(json.load(f))
        except (OSError, json.JSONDecodeError):
            continue
    return out


class HistoryServer:
    """Serves an archive directory over HTTP (``HistoryServer`` analog):
    ``/jobs`` (summaries), ``/jobs/<id>`` (full archived document),
    ``/overview``."""

    def __init__(self, archive_dir: str, host: str = "127.0.0.1",
                 port: int = 0, ssl_context=None):
        self.archive_dir = archive_dir
        self._ssl = ssl_context
        adir = archive_dir

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def _send(self, obj, status: int = 200):
                data = json.dumps(obj, default=str).encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0].rstrip("/")
                if path.startswith("/jobs/"):
                    # direct file open — no full-archive scan per lookup
                    job_id = os.path.basename(path.split("/", 2)[2])
                    fp = os.path.join(adir, f"{job_id}.json")
                    try:
                        with open(fp) as f:
                            return self._send(json.load(f))
                    except (OSError, json.JSONDecodeError):
                        return self._send(
                            {"error": f"no archived job {job_id}"}, 404)
                jobs = list_archived(adir)
                if path in ("", "/jobs"):
                    return self._send({"jobs": [
                        {"id": j.get("id"), "state": j.get("state"),
                         "name": j.get("name"),
                         "archived_at": j.get("archived_at")}
                        for j in jobs]})
                if path == "/overview":
                    return self._send({
                        "jobs_total": len(jobs),
                        "by_state": _count_by_state(jobs)})
                return self._send({"error": "not found"}, 404)

        self._server = ThreadingHTTPServer((host, port), Handler)
        if ssl_context is not None:
            self._server.socket = ssl_context.wrap_socket(
                self._server.socket, server_side=True)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="history-server", daemon=True)

    def start(self) -> "HistoryServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def url(self) -> str:
        scheme = "https" if self._ssl is not None else "http"
        return f"{scheme}://{self.host}:{self.port}"


def _count_by_state(jobs: List[Dict[str, Any]]) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for j in jobs:
        out[j.get("state", "?")] = out.get(j.get("state", "?"), 0) + 1
    return out
