"""Server-rendered dashboard views: job DAG SVG, flame graph SVG,
checkpoint-history and per-subtask backpressure HTML fragments.

The reference ships a 17k-LoC Angular SPA (``flink-runtime-web/
web-dashboard``: dagre DAG view, d3-flame-graph, checkpoint drill-down,
per-subtask backpressure); this framework renders the same four views
server-side as SVG/HTML fragments the embedded dashboard injects — which
also makes them assertable by automated DOM tests (parse the markup, no
browser needed)."""

from __future__ import annotations

import html
from typing import Any, Dict, List, Optional


def _esc(s: Any) -> str:
    return html.escape(str(s), quote=True)


# ---------------------------------------------------------------------------
# job DAG (dagre-analog layered layout)
# ---------------------------------------------------------------------------

def plan_svg(plan: Dict[str, Any]) -> str:
    """ExecutionPlan view -> layered SVG.  ``plan``: {"vertices": [{id,
    name, parallelism}], "edges": [{source, target, partitioning}]}.
    Layers = longest-path depth from sources; vertices are rounded rects,
    edges cubic paths labeled with their partitioning."""
    vertices = plan.get("vertices", [])
    edges = plan.get("edges", [])
    depth: Dict[Any, int] = {v["id"]: 0 for v in vertices}
    for _ in range(len(vertices)):
        for e in edges:
            if e["source"] in depth and e["target"] in depth:
                depth[e["target"]] = max(depth[e["target"]],
                                         depth[e["source"]] + 1)
    layers: Dict[int, List[dict]] = {}
    for v in vertices:
        layers.setdefault(depth[v["id"]], []).append(v)
    BW, BH, HGAP, VGAP, PAD = 190, 54, 90, 28, 24
    pos: Dict[Any, tuple] = {}
    max_rows = max((len(vs) for vs in layers.values()), default=1)
    for d in sorted(layers):
        for i, v in enumerate(layers[d]):
            x = PAD + d * (BW + HGAP)
            y = PAD + i * (BH + VGAP)
            pos[v["id"]] = (x, y)
    width = PAD * 2 + (max(layers, default=0) + 1) * (BW + HGAP) - HGAP
    height = PAD * 2 + max_rows * (BH + VGAP) - VGAP
    parts = [f'<svg xmlns="http://www.w3.org/2000/svg" class="job-dag" '
             f'viewBox="0 0 {width} {height}" width="{width}" '
             f'height="{height}">']
    for e in edges:
        if e["source"] not in pos or e["target"] not in pos:
            continue
        x1, y1 = pos[e["source"]]
        x2, y2 = pos[e["target"]]
        sx, sy = x1 + BW, y1 + BH / 2
        tx, ty = x2, y2 + BH / 2
        mx = (sx + tx) / 2
        parts.append(
            f'<path class="dag-edge" d="M {sx} {sy} C {mx} {sy}, '
            f'{mx} {ty}, {tx} {ty}" fill="none" stroke="#8b949e" '
            f'stroke-width="1.5" marker-end="url(#arr)"/>')
        label = _esc(e.get("partitioning", ""))
        if label:
            parts.append(f'<text class="dag-edge-label" x="{mx}" '
                         f'y="{(sy + ty) / 2 - 5}" font-size="10" '
                         f'fill="#8b949e" text-anchor="middle">{label}'
                         f'</text>')
    parts.append('<defs><marker id="arr" viewBox="0 0 10 10" refX="9" '
                 'refY="5" markerWidth="7" markerHeight="7" '
                 'orient="auto-start-reverse">'
                 '<path d="M 0 0 L 10 5 L 0 10 z" fill="#8b949e"/>'
                 '</marker></defs>')
    for v in vertices:
        x, y = pos[v["id"]]
        name = _esc(v.get("name", v["id"]))
        parts.append(
            f'<g class="dag-vertex" data-vertex-id="{_esc(v["id"])}">'
            f'<rect x="{x}" y="{y}" width="{BW}" height="{BH}" rx="8" '
            f'fill="#1c2430" stroke="#2f81f7" stroke-width="1.5"/>'
            f'<text x="{x + BW / 2}" y="{y + 22}" font-size="12" '
            f'fill="#e6edf3" text-anchor="middle">{name}</text>'
            f'<text x="{x + BW / 2}" y="{y + 40}" font-size="10" '
            f'fill="#8b949e" text-anchor="middle">parallelism '
            f'{_esc(v.get("parallelism", 1))}</text></g>')
    parts.append("</svg>")
    return "".join(parts)


# ---------------------------------------------------------------------------
# flame graph (d3-flame-graph analog, static SVG)
# ---------------------------------------------------------------------------

def flamegraph_svg(tree: Dict[str, Any], width: int = 1000,
                   row_h: int = 18, max_depth: int = 40) -> str:
    """{name, value, children} tree -> icicle-layout SVG (root at top)."""
    total = max(tree.get("value", 0), 1)

    rects: List[str] = []
    depth_max = 0

    def walk(node, x0: float, x1: float, depth: int):
        nonlocal depth_max
        if depth > max_depth or x1 - x0 < 0.5:
            return
        depth_max = max(depth_max, depth)
        w = x1 - x0
        name = _esc(node.get("name", ""))
        pct = 100.0 * node.get("value", 0) / total
        hue = 20 + (hash(name) % 20)
        rects.append(
            f'<g class="flame-frame" data-depth="{depth}">'
            f'<rect x="{x0:.2f}" y="{depth * row_h}" width="{w:.2f}" '
            f'height="{row_h - 1}" fill="hsl({hue},85%,{60 - depth % 3 * 4}%)"'
            f'><title>{name} — {node.get("value", 0)} samples '
            f'({pct:.1f}%)</title></rect>')
        if w > 40:
            shown = name if len(name) * 6 < w else name[: int(w / 6)] + "…"
            # style (not attribute): survives the dashboard's
            # `#flame text{fill:#fff}` ID-selector rule
            rects.append(
                f'<text x="{x0 + 3:.2f}" y="{depth * row_h + 13}" '
                f'font-size="10" style="fill:#1a1a1a">{shown}</text>')
        rects.append("</g>")
        x = x0
        for c in node.get("children", []):
            cw = w * c.get("value", 0) / max(node.get("value", 1), 1)
            walk(c, x, x + cw, depth + 1)
            x += cw

    walk(tree, 0.0, float(width), 0)
    height = (depth_max + 1) * row_h
    return (f'<svg xmlns="http://www.w3.org/2000/svg" class="flamegraph" '
            f'viewBox="0 0 {width} {height}" width="100%" '
            f'height="{height}">' + "".join(rects) + "</svg>")


# ---------------------------------------------------------------------------
# checkpoint drill-down + per-subtask backpressure (HTML fragments)
# ---------------------------------------------------------------------------

def checkpoints_html(history: List[Dict[str, Any]],
                     completed_ids: List[int]) -> str:
    """Checkpoint-history drill-down table (CheckpointStatsTracker view)."""
    rows = []
    done = set(completed_ids)
    for cp in history:
        cid = cp.get("id")
        state = cp.get("state") or ("COMPLETED" if cid in done
                                    else "IN_PROGRESS")
        rows.append(
            f'<tr class="ckpt-row" data-checkpoint-id="{_esc(cid)}">'
            f'<td>{_esc(cid)}</td><td>{_esc(state)}</td>'
            f'<td>{_esc(cp.get("duration_ms", "—"))}</td>'
            f'<td>{_esc(cp.get("state_size_bytes", "—"))}</td>'
            f'<td>{_esc(cp.get("kind", "checkpoint"))}</td></tr>')
    if not rows:
        rows.append('<tr class="ckpt-row"><td colspan="5">no checkpoints '
                    'yet</td></tr>')
    return ('<table class="ckpt-table"><thead><tr><th>id</th><th>state</th>'
            '<th>duration (ms)</th><th>size (bytes)</th><th>kind</th>'
            '</tr></thead><tbody>' + "".join(rows) + "</tbody></table>")


def device_health_html(status: Dict[str, Any]) -> str:
    """Device-lane health panel (``job_status()["device_health"]``): tier
    state badge + watchdog/quarantine/heal counters.  Server-rendered, DOM
    -testable — same pattern as the checkpoint drill-down."""
    state = str(status.get("state", "healthy"))
    cls = "dh-healthy" if state == "healthy" else "dh-quarantined"
    rows = []
    for label, key in (("quarantines", "quarantines"),
                       ("heals", "heals"),
                       ("watchdog timeouts", "watchdog_timeouts"),
                       ("watchdog near-misses", "near_misses"),
                       ("transient retries", "transient_retries"),
                       ("OOM page-outs", "oom_pageouts"),
                       ("degraded operators", "degraded_operators"),
                       ("tier migrations", "quarantine_migrations"),
                       ("re-promotions", "repromotions")):
        rows.append(f'<tr class="dh-row" data-metric="{_esc(key)}">'
                    f'<td>{_esc(label)}</td>'
                    f'<td>{_esc(status.get(key, 0))}</td></tr>')
    failure = status.get("last_failure")
    detail = (f'<div class="dh-failure">last failure: {_esc(failure)}</div>'
              if failure else "")
    return (f'<div class="dh-panel">'
            f'<span class="dh-state {cls}" data-state="{_esc(state)}">'
            f'device tier: {_esc(state)}</span>{detail}'
            f'<table class="dh-table"><thead><tr><th>metric</th>'
            f'<th>value</th></tr></thead><tbody>' + "".join(rows)
            + "</tbody></table></div>")


def autoscaler_html(status: Dict[str, Any]) -> str:
    """Reactive-autoscaler panel (``job_status()["autoscaler"]``): the
    rescale lifecycle's state badge, current→target parallelism, the
    rescale/rollback/re-trigger counters, cooldown, the parallelism path
    the job has walked, and the last observed signals.  Server-rendered,
    DOM-testable — same pattern as the device-health panel."""
    if not status:
        return ('<div class="as-panel"><span class="as-state as-off" '
                'data-state="off">autoscaler: off</span></div>')
    state = str(status.get("state", "?"))
    cur = status.get("current_parallelism", "?")
    tgt = status.get("target_parallelism", "?")
    cls = ("as-rescaling" if state == "Restarting" else "as-running")
    rows = []
    for label, key in (("rescales", "rescales"),
                       ("rollbacks", "rollbacks"),
                       ("re-triggers", "retriggers"),
                       ("rescales skipped", "rescales_skipped"),
                       ("last rescale duration (ms)",
                        "last_rescale_duration_ms"),
                       ("cooldown remaining (ms)", "cooldown_remaining_ms"),
                       ("min parallelism", "min_parallelism"),
                       ("max parallelism", "max_parallelism")):
        rows.append(f'<tr class="as-row" data-metric="{_esc(key)}">'
                    f'<td>{_esc(label)}</td>'
                    f'<td>{_esc(status.get(key, 0))}</td></tr>')
    path = " → ".join(str(p) for p in status.get("parallelism_path", []))
    sig = status.get("signals") or {}
    sig_items = "".join(
        f'<span class="as-signal" data-signal="{_esc(k)}">'
        f'{_esc(k)}={_esc(v)}</span> ' for k, v in sorted(sig.items()))
    return (f'<div class="as-panel">'
            f'<span class="as-state {cls}" data-state="{_esc(state)}">'
            f'autoscaler: {_esc(state)} · parallelism {_esc(cur)} → '
            f'{_esc(tgt)}</span>'
            f'<div class="as-path" data-path="{_esc(path)}">path: '
            f'{_esc(path)}</div>'
            f'<div class="as-signals">{sig_items}</div>'
            f'<table class="as-table"><thead><tr><th>metric</th>'
            f'<th>value</th></tr></thead><tbody>' + "".join(rows)
            + "</tbody></table></div>")


def ha_html(status: Dict[str, Any]) -> str:
    """Coordinator-HA panel (``job_status()["ha"]``): leader/demoted
    badge, the fencing epoch every control message carries, the lease
    holder + deadline, which source recovery restored from, and the
    stale-epoch rejection counters.  Server-rendered, DOM-testable —
    same pattern as the autoscaler panel."""
    if not status or not status.get("enabled"):
        return ('<div class="ha-panel"><span class="ha-state ha-off" '
                'data-state="off">ha: off</span></div>')
    demoted = bool(status.get("demoted"))
    state = "demoted" if demoted else "leading"
    cls = "ha-demoted" if demoted else "ha-leading"
    epoch = status.get("leader_epoch", 0)
    rows = []
    for label, key in (("job id", "job_id"),
                       ("lease holder", "holder"),
                       ("lease deadline (unix s)", "lease_deadline"),
                       ("restore source", "restore_source"),
                       ("fenced completions", "fenced_completions"),
                       ("fenced worker msgs", "fenced_worker_msgs")):
        rows.append(f'<tr class="ha-row" data-metric="{_esc(key)}">'
                    f'<td>{_esc(label)}</td>'
                    f'<td>{_esc(status.get(key, ""))}</td></tr>')
    return (f'<div class="ha-panel">'
            f'<span class="ha-state {cls}" data-state="{_esc(state)}" '
            f'data-epoch="{_esc(epoch)}">'
            f'ha: {_esc(state)} · epoch {_esc(epoch)}</span>'
            f'<table class="ha-table"><thead><tr><th>field</th>'
            f'<th>value</th></tr></thead><tbody>' + "".join(rows)
            + "</tbody></table></div>")


def queryable_html(stats: Dict[str, Any]) -> str:
    """Queryable serving tier panel (``job_status()["queryable"]``):
    per-state lookup volume/latency + replica staleness and shard
    manifests.  Server-rendered, DOM-testable — same pattern as the
    device-health panel."""
    per_state = stats.get("per_state", {})
    lag = stats.get("replica_lag_checkpoints", 0)
    protocols = stats.get("protocols") or {}
    head = (f'<div class="qs-summary" '
            f'data-lookups="{_esc(stats.get("lookups_total", 0))}" '
            f'data-serve-p99="{_esc(stats.get("serve_p99_ms"))}" '
            f'data-cache-hit-rate='
            f'"{_esc(stats.get("cache_hit_rate", 0))}" '
            f'data-replica-lag="{_esc(lag)}">'
            f'lookups {_esc(stats.get("lookups_total", 0))} · '
            f'{_esc(stats.get("lookups_per_sec", 0))}/s · '
            # both latency readings, labelled: the SERVER-side service
            # time (lookup + serialization in the handler) is the honest
            # serve cost; the lookup p99 excludes serialization
            f'serve p99 {_esc(stats.get("serve_p99_ms"))} ms '
            f'(server-side) · '
            f'lookup p99 {_esc(stats.get("lookup_p99_ms"))} ms · '
            f'binary {_esc(protocols.get("binary", 0))} / '
            f'json {_esc(protocols.get("json", 0))} · '
            f'cache hit {_esc(stats.get("cache_hit_rate", 0))} · '
            f'replica lag {_esc(lag)} ckpts / '
            f'{_esc(stats.get("replica_lag_ms", 0))} ms</div>')
    rows = []
    for name in sorted(per_state):
        s = per_state[name]
        rep = s.get("replica", {})
        laggards = ",".join(rep.get("laggards", [])) or "-"
        rows.append(
            f'<tr class="qs-row" data-state="{_esc(name)}" '
            f'data-laggards="{_esc(laggards)}">'
            f'<td>{_esc(name)}</td>'
            f'<td>{_esc(s.get("lookups", 0))}</td>'
            f'<td>{_esc(s.get("lookup_p50_ms"))}</td>'
            f'<td>{_esc(s.get("lookup_p99_ms"))}</td>'
            f'<td>{_esc(rep.get("serving_checkpoint_id"))}</td>'
            f'<td>{_esc(rep.get("replica_lag_checkpoints", 0))}</td>'
            f'<td>{_esc(rep.get("replicas", 1))}</td>'
            f'<td>{_esc(laggards)}</td>'
            f'<td>{_esc(len(rep.get("shards", [])))}</td></tr>')
    return (f'<div class="qs-panel">{head}'
            f'<table class="qs-table"><thead><tr><th>state</th>'
            f'<th>lookups</th><th>p50 ms</th><th>p99 ms</th>'
            f'<th>serving ckpt</th><th>lag</th><th>replicas</th>'
            f'<th>laggards</th><th>shards</th>'
            f'</tr></thead><tbody>' + "".join(rows)
            + "</tbody></table></div>")


def latency_html(hops: List[Dict[str, Any]]) -> str:
    """Per-(source, operator-hop) latency panel
    (``job_status()["latency"]`` rows from the LatencyMarker flow):
    p50/p95/p99/max per hop.  Server-rendered, DOM-testable — same
    pattern as the device-health panel."""
    if not hops:
        return ('<div class="lat-panel" data-hops="0">no latency markers '
                'recorded — set metrics.latency.interval to enable</div>')
    rows = []
    for h in hops:
        rows.append(
            f'<tr class="lat-row" data-source="{_esc(h["source"])}" '
            f'data-hop="{_esc(h["hop"])}">'
            f'<td>{_esc(h["source"])}[{_esc(h["source_subtask"])}]</td>'
            f'<td>{_esc(h["hop"])}</td>'
            f'<td>{_esc(h["count"])}</td>'
            f'<td>{_esc(h["p50_ms"])}</td>'
            f'<td>{_esc(h["p95_ms"])}</td>'
            f'<td>{_esc(h["p99_ms"])}</td>'
            f'<td>{_esc(h["max_ms"])}</td></tr>')
    return (f'<div class="lat-panel" data-hops="{len(hops)}">'
            f'<table class="lat-table"><thead><tr><th>source</th>'
            f'<th>hop</th><th>samples</th><th>p50 ms</th><th>p95 ms</th>'
            f'<th>p99 ms</th><th>max ms</th></tr></thead><tbody>'
            + "".join(rows) + "</tbody></table></div>")


def backpressure_html(vertices: List[Dict[str, Any]],
                      checkpoints: Optional[Dict[str, Any]] = None) -> str:
    """Per-SUBTASK busy/backpressure/idle bars (the reference's subtask
    backpressure tab), one row per subtask under its vertex — plus, when
    present, the per-channel queue-depth/backpressured-time table and the
    checkpoint-alignment summary of the unaligned-checkpoint path (same
    server-rendered, DOM-testable pattern as the device-health panel)."""
    out = ['<div class="bp-view">']
    cp = checkpoints or {}
    if cp:
        out.append(
            f'<div class="bp-alignment">'
            f'<span class="bp-align-item" data-metric='
            f'"last_alignment_duration_ms">alignment '
            f'{_esc(cp.get("last_alignment_duration_ms", 0))} ms</span>'
            f'<span class="bp-align-item" data-metric='
            f'"last_overtaken_bytes">overtaken '
            f'{_esc(cp.get("last_overtaken_bytes", 0))} B</span>'
            f'<span class="bp-align-item" data-metric='
            f'"last_persisted_inflight_bytes">persisted in-flight '
            f'{_esc(cp.get("last_persisted_inflight_bytes", 0))} B</span>'
            f'<span class="bp-align-item" data-metric='
            f'"unaligned_checkpoints">unaligned checkpoints '
            f'{_esc(cp.get("unaligned_checkpoints", 0))}</span></div>')
    for v in vertices:
        out.append(f'<div class="bp-vertex" data-vertex-id='
                   f'"{_esc(v["id"])}"><h3>{_esc(v.get("name", v["id"]))}'
                   f"</h3>")
        for s in v.get("subtasks", []):
            busy = float(s.get("busy_ratio", 0))
            bp = float(s.get("backpressure_ratio", 0))
            idle = float(s.get("idle_ratio", 0))
            out.append(
                f'<div class="bp-subtask" data-subtask='
                f'"{_esc(s.get("index"))}">'
                f'<span class="bp-label">#{_esc(s.get("index"))} '
                f'{_esc(s.get("state", ""))}</span>'
                f'<div class="bp-bar">'
                f'<div class="bp-busy" style="width:{busy * 100:.1f}%">'
                f"</div>"
                f'<div class="bp-backpressured" '
                f'style="width:{bp * 100:.1f}%"></div>'
                f'<div class="bp-idle" style="width:{idle * 100:.1f}%">'
                f"</div></div>"
                f'<span class="bp-pct">busy {busy * 100:.0f}% · bp '
                f'{bp * 100:.0f}% · idle {idle * 100:.0f}%</span>')
            chans = s.get("channels") or []
            if chans:
                rows = "".join(
                    f'<tr class="bp-chan" data-channel="{_esc(c["name"])}">'
                    f'<td>{_esc(c["name"])}</td><td>{_esc(c["depth"])}</td>'
                    f'<td>{_esc(c.get("queued_bytes", 0))}</td>'
                    f'<td>{_esc(c.get("backpressured_ms", 0))}</td></tr>'
                    for c in chans)
                out.append(
                    f'<table class="bp-chan-table" data-alignment-queued='
                    f'"{_esc(s.get("alignment_queued", 0))}">'
                    f'<thead><tr><th>channel</th><th>depth</th>'
                    f'<th>queued bytes</th><th>backpressured (ms)</th>'
                    f'</tr></thead><tbody>{rows}</tbody></table>')
            out.append("</div>")
        out.append("</div>")
    out.append("</div>")
    return "".join(out)
