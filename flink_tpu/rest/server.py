"""REST API + web dashboard.

Analog of the reference's REST endpoint (``RestServerEndpoint`` + ~100
typed handlers in ``runtime/rest/handler/job/*`` + the Angular dashboard of
``flink-runtime-web``): a threaded HTTP server over the MiniCluster's job
registry serving reference-shaped JSON plus a single-page dashboard that
polls it.

Endpoints:
  GET  /overview                      cluster overview
  GET  /jobs                          job listing
  GET  /jobs/<id>                     topology + per-vertex gauges
  GET  /jobs/<id>/checkpoints         completed checkpoint stats
  GET  /jobs/<id>/backpressure        busy/idle/backpressured per vertex
  GET  /jobs/<id>/metrics             numeric metrics incl. latency pcts
  GET  /jobs/<id>/autoscaler(.html)   reactive-autoscaler rescale status
  GET  /jobs/<id>/ha(.html)           coordinator HA: leader epoch + fences
  GET  /jobs/<id>/exceptions          root failure cause
  GET  /jobs/<id>/flamegraph          sampled task-thread flame graph
  POST /jobs/<id>/savepoints          trigger a savepoint
  PATCH /jobs/<id>                    cancel
  GET  /                              dashboard (HTML)
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class JobRegistry:
    """Named running/finished jobs the REST layer exposes."""

    def __init__(self):
        self._jobs: Dict[str, Tuple[str, Any]] = {}  # id -> (name, cluster)
        self._lock = threading.Lock()
        self._n = 0

    def register(self, name: str, cluster) -> str:
        with self._lock:
            self._n += 1
            job_id = f"job-{self._n:04d}"
            self._jobs[job_id] = (name, cluster)
            return job_id

    def jobs(self) -> List[Tuple[str, str, Any]]:
        with self._lock:
            return [(jid, name, c) for jid, (name, c) in self._jobs.items()]

    def get(self, job_id: str):
        with self._lock:
            return self._jobs.get(job_id)


def _percentiles(xs: List[float]) -> Dict[str, float]:
    if not xs:
        return {}
    a = np.asarray(xs)
    return {"p50": float(np.percentile(a, 50)),
            "p95": float(np.percentile(a, 95)),
            "p99": float(np.percentile(a, 99)),
            "max": float(a.max()), "count": len(xs)}


class MetricsHistory:
    """Per-job ring of periodic metric samples — the
    ``MetricStore``/``MetricFetcher`` analog behind the dashboard's
    per-operator graphs.  Sampled by the REST server's background thread;
    each sample is (wall ms, {vertex id: {records_in, records_out,
    busy_ratio, backpressure_ratio}})."""

    def __init__(self, capacity: int = 240):
        self.capacity = capacity
        self._lock = threading.Lock()
        self._series: Dict[str, List[dict]] = {}

    def sample(self, job_id: str, status: Dict[str, Any]) -> None:
        import time as _time
        entry = {"ts": int(_time.time() * 1000),
                 "vertices": {v["id"]: {
                     "records_in": v["records_in"],
                     "records_out": v["records_out"],
                     "busy_ratio": round(v.get("busy_ratio", 0.0), 4),
                     "backpressure_ratio": round(
                         v.get("backpressure_ratio", 0.0), 4)}
                     for v in status.get("vertices", [])}}
        with self._lock:
            ring = self._series.setdefault(job_id, [])
            ring.append(entry)
            del ring[:-self.capacity]

    def series(self, job_id: str) -> List[dict]:
        with self._lock:
            return list(self._series.get(job_id, []))


class RestServer:
    def __init__(self, registry: JobRegistry, host: str = "127.0.0.1",
                 port: int = 0, ssl_context=None,
                 auth_token: Optional[str] = None,
                 sample_interval_s: float = 1.0):
        """``ssl_context``: server-side TLS (``security.ssl.rest.enabled``
        analog); ``auth_token``: require ``Authorization: Bearer <token>``
        on every request.  A background thread samples every job's
        per-vertex metrics into ``MetricsHistory`` each
        ``sample_interval_s`` (the dashboard's graphs-over-time feed)."""
        self.registry = registry
        self._ssl = ssl_context
        self.history = MetricsHistory()
        self._sample_interval_s = sample_interval_s
        self._stop_sampling = threading.Event()
        registry_ref = registry
        token_ref = auth_token
        history_ref = self.history

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def parse_request(self):
                ok = super().parse_request()
                if ok and token_ref is not None:
                    import hmac as _hmac
                    got = self.headers.get("Authorization", "")
                    if not _hmac.compare_digest(got.encode(),
                                                f"Bearer {token_ref}".encode()):
                        self.send_error(401, "missing or wrong bearer token")
                        return False
                return ok

            def _send(self, obj, status: int = 200,
                      content_type: str = "application/json"):
                data = (obj if isinstance(obj, bytes)
                        else json.dumps(obj, default=str).encode())
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _job(self, job_id: str):
                entry = registry_ref.get(job_id)
                if entry is None:
                    self._send({"error": f"no job {job_id}"}, 404)
                    return None
                return entry

            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0].rstrip("/")
                if path == "" or path == "/index.html":
                    return self._send(_DASHBOARD_HTML.encode(),
                                      content_type="text/html")
                if path == "/overview":
                    jobs = registry_ref.jobs()
                    states = [c.job_status()["state"] for _, _, c in jobs]
                    return self._send({
                        "jobs_total": len(jobs),
                        "jobs_running": states.count("RUNNING"),
                        "jobs_finished": states.count("FINISHED"),
                        "jobs_failed": states.count("FAILED")})
                if path == "/jobs":
                    return self._send({"jobs": [
                        {"id": jid, "name": name,
                         "state": c.job_status()["state"]}
                        for jid, name, c in registry_ref.jobs()]})
                m = re.match(r"^/jobs/([^/]+)(?:/(.*))?$", path)
                if not m:
                    return self._send({"error": "not found"}, 404)
                entry = self._job(m.group(1))
                if entry is None:
                    return
                name, cluster = entry
                sub = m.group(2) or ""
                status = cluster.job_status()
                if sub == "":
                    return self._send({"id": m.group(1), "name": name,
                                       **status})
                if sub == "checkpoints":
                    return self._send({
                        "completed": status["completed_checkpoints"],
                        "count": len(status["completed_checkpoints"]),
                        # per-checkpoint duration/size history
                        # (CheckpointStatsTracker analog)
                        "history": status.get("checkpoint_stats", [])})
                if sub == "watermarks":
                    return self._send({"vertices": [
                        {"id": v["id"], "watermark": v.get("watermark")}
                        for v in status["vertices"]]})
                if sub == "backpressure":
                    ck = status.get("checkpoints", {})
                    return self._send({"vertices": [
                        {"id": v["id"],
                         "busy": round(v["busy_ratio"], 4),
                         "idle": round(v["idle_ratio"], 4),
                         "backpressured": round(v["backpressure_ratio"], 4),
                         # per-channel queue depth / backpressured time +
                         # the alignment-queue gauge (unaligned ckpts)
                         "subtasks": [
                             {"index": s["index"],
                              "channels": s.get("channels", []),
                              "alignment_queued":
                                  s.get("alignment_queued", 0)}
                             for s in v.get("subtasks", [])]}
                        for v in status["vertices"]],
                        "checkpoints": {
                            k: ck.get(k, 0) for k in (
                                "last_alignment_duration_ms",
                                "last_overtaken_bytes",
                                "last_persisted_inflight_bytes",
                                "unaligned_checkpoints")}})
                if sub == "metrics":
                    return self._send({
                        "records_in": sum(v["records_in"]
                                          for v in status["vertices"]),
                        "records_out": sum(v["records_out"]
                                           for v in status["vertices"]),
                        "latency_ms": _percentiles(
                            cluster.sink_latencies_ms())})
                if sub == "latency":
                    # per-(source, operator-hop) percentiles from the
                    # LatencyMarker flow + the legacy sink rollup
                    return self._send({
                        "hops": status.get("latency", []),
                        "sink_latency_ms": _percentiles(
                            cluster.sink_latencies_ms())})
                if sub == "latency.html":
                    from flink_tpu.rest.views import latency_html
                    return self._send(
                        latency_html(status.get("latency", [])).encode(),
                        content_type="text/html")
                if sub == "trace":
                    # Chrome trace-event JSON of the span journal
                    # (Perfetto-viewable; trace summary in job_status)
                    fn = getattr(cluster, "trace_events", None)
                    if fn is None:
                        return self._send(
                            {"traceEvents": [], "displayTimeUnit": "ms",
                             "otherData": {"enabled": False}})
                    return self._send(fn())
                if sub == "metrics/history":
                    return self._send(
                        {"series": history_ref.series(m.group(1))})
                if sub == "exceptions":
                    return self._send({
                        "root_exception": status["failure"],
                        "history": status.get("exception_history", [])})
                if sub == "flamegraph":
                    from flink_tpu.rest.flamegraph import flamegraph
                    # scope to THIS job's subtask threads — concurrent jobs
                    # must not pollute each other's profiles
                    names = {f"task-{t.vertex_uid}-{t.subtask_index}"
                             for t in getattr(cluster, "_tasks", [])}
                    return self._send(flamegraph(duration_ms=150,
                                                 thread_names=names))
                if sub == "plan":
                    view = getattr(cluster, "execution_plan_view",
                                   lambda: {"vertices": [], "edges": []})()
                    return self._send(view)
                # ---- server-rendered dashboard views (views.py): DAG svg,
                # flame svg, checkpoint table, per-subtask backpressure —
                # DOM-testable without a browser
                if sub == "plan.svg":
                    from flink_tpu.rest.views import plan_svg
                    view = getattr(cluster, "execution_plan_view",
                                   lambda: {"vertices": [], "edges": []})()
                    return self._send(plan_svg(view).encode(),
                                      content_type="image/svg+xml")
                if sub == "flamegraph.svg":
                    from flink_tpu.rest.flamegraph import flamegraph
                    from flink_tpu.rest.views import flamegraph_svg
                    names = {f"task-{t.vertex_uid}-{t.subtask_index}"
                             for t in getattr(cluster, "_tasks", [])}
                    tree = flamegraph(duration_ms=150, thread_names=names)
                    return self._send(flamegraph_svg(tree).encode(),
                                      content_type="image/svg+xml")
                if sub == "checkpoints.html":
                    from flink_tpu.rest.views import checkpoints_html
                    frag = checkpoints_html(
                        status.get("checkpoint_stats", []),
                        status["completed_checkpoints"])
                    return self._send(frag.encode(),
                                      content_type="text/html")
                if sub == "backpressure.html":
                    from flink_tpu.rest.views import backpressure_html
                    return self._send(
                        backpressure_html(
                            status["vertices"],
                            status.get("checkpoints", {})).encode(),
                        content_type="text/html")
                if sub == "queryable":
                    return self._send(status.get(
                        "queryable", {"states": [], "lookups_total": 0}))
                if sub == "queryable.html":
                    from flink_tpu.rest.views import queryable_html
                    return self._send(queryable_html(
                        status.get("queryable", {})).encode(),
                        content_type="text/html")
                if sub.startswith("state/"):
                    # GET /jobs/<id>/state/<name>/<key>?consistency=live
                    qsvc = getattr(cluster, "queryable", None)
                    if qsvc is None:
                        return self._send(
                            {"error": "queryable serving tier not enabled"},
                            404)
                    parts = sub.split("/", 2)
                    if len(parts) != 3 or not parts[2]:
                        return self._send({"error": "state/<name>/<key>"},
                                          404)
                    name, raw = parts[1], parts[2]
                    from urllib.parse import parse_qs, unquote, urlparse
                    q = parse_qs(urlparse(self.path).query)
                    cons = (q.get("consistency") or ["live"])[0]
                    raw = unquote(raw)
                    try:
                        key: Any = int(raw)
                    except ValueError:
                        key = raw
                    st, value = qsvc.lookup_batch(name, [key], cons)
                    if st != "ok":
                        return self._send({"error": value}, 400)
                    if not value["found"][0]:
                        return self._send({"error": f"no state for key "
                                                    f"{key!r}",
                                           "tags": value["tags"]}, 404)
                    return self._send({"key": key,
                                       "value": value["values"][0],
                                       "tags": value["tags"]})
                if sub == "device_health":
                    return self._send(status.get(
                        "device_health", {"state": "healthy"}))
                if sub == "device_health.html":
                    from flink_tpu.rest.views import device_health_html
                    return self._send(device_health_html(
                        status.get("device_health", {})).encode(),
                        content_type="text/html")
                if sub == "autoscaler":
                    return self._send(status.get(
                        "autoscaler", {"state": "off"}))
                if sub == "autoscaler.html":
                    from flink_tpu.rest.views import autoscaler_html
                    return self._send(autoscaler_html(
                        status.get("autoscaler", {})).encode(),
                        content_type="text/html")
                if sub == "ha":
                    return self._send(status.get("ha", {"enabled": False}))
                if sub == "ha.html":
                    from flink_tpu.rest.views import ha_html
                    return self._send(ha_html(
                        status.get("ha", {})).encode(),
                        content_type="text/html")
                return self._send({"error": f"unknown path {sub}"}, 404)

            def do_POST(self):  # noqa: N802
                path = self.path.split("?")[0].rstrip("/")
                mb = re.match(r"^/jobs/([^/]+)/state/([^/:]+):batch$", path)
                if mb:
                    # POST /jobs/<id>/state/<name>:batch
                    # body: {"keys": [...], "consistency": "live|checkpoint"}
                    entry = self._job(mb.group(1))
                    if entry is None:
                        return
                    qsvc = getattr(entry[1], "queryable", None)
                    if qsvc is None:
                        return self._send(
                            {"error": "queryable serving tier not enabled"},
                            404)
                    try:
                        n = int(self.headers.get("Content-Length", 0))
                        body = json.loads(self.rfile.read(n) or b"{}")
                        keys = body["keys"]
                        assert isinstance(keys, list)
                    except (ValueError, KeyError, AssertionError):
                        return self._send(
                            {"error": "body must be JSON with a 'keys' "
                                      "list"}, 400)
                    st, value = qsvc.lookup_batch(
                        mb.group(2), keys,
                        body.get("consistency", "live"))
                    if st != "ok":
                        return self._send({"error": value}, 400)
                    return self._send(value)
                m = re.match(r"^/jobs/([^/]+)/(savepoints|stop)$", path)
                if not m:
                    return self._send({"error": "not found"}, 404)
                entry = self._job(m.group(1))
                if entry is None:
                    return
                _name, cluster = entry
                if m.group(2) == "stop":
                    # stop-with-savepoint (`flink stop` analog)
                    sp = cluster.stop_with_savepoint()
                    if sp is None:
                        return self._send({"status": "failed"}, 409)
                    return self._send({"status": "stopped",
                                       "checkpoint_id": sp})
                sp = cluster.savepoint()
                if sp is None:
                    return self._send({"status": "failed"}, 409)
                return self._send({"status": "completed", "checkpoint_id": sp})

            def do_PATCH(self):  # noqa: N802
                m = re.match(r"^/jobs/([^/]+)$", self.path.rstrip("/"))
                if not m:
                    return self._send({"error": "not found"}, 404)
                entry = self._job(m.group(1))
                if entry is None:
                    return
                entry[1].cancel()
                return self._send({"status": "cancelling"}, 202)

        self._server = ThreadingHTTPServer((host, port), Handler)
        if ssl_context is not None:
            self._server.socket = ssl_context.wrap_socket(
                self._server.socket, server_side=True)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="rest-server", daemon=True)
        self._sampler = threading.Thread(target=self._sample_loop,
                                         name="rest-metrics-sampler",
                                         daemon=True)

    def _sample_loop(self) -> None:
        terminal_done: set = set()
        while not self._stop_sampling.wait(self._sample_interval_s):
            for jid, _name, cluster in self.registry.jobs():
                if jid in terminal_done:
                    continue       # frozen: keep the run's real history
                try:
                    status = cluster.job_status()
                    self.history.sample(jid, status)
                    if status.get("state") in ("FINISHED", "FAILED",
                                               "CANCELED"):
                        # one final sample then freeze — endless flatline
                        # samples would evict the run's actual series
                        terminal_done.add(jid)
                except Exception:  # noqa: BLE001 — a finished/torn-down
                    pass           # job must not kill the sampler

    def start(self) -> "RestServer":
        self._thread.start()
        self._sampler.start()
        return self

    def stop(self) -> None:
        self._stop_sampling.set()
        self._server.shutdown()
        self._server.server_close()

    @property
    def url(self) -> str:
        scheme = "https" if self._ssl is not None else "http"
        return f"{scheme}://{self.host}:{self.port}"


_DASHBOARD_HTML = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title>flink-tpu dashboard</title>
<style>
 :root{color-scheme:light;
   --surface:#fcfcfb;--panel:#f3f3f1;--border:#d9d8d4;
   --text:#0b0b0b;--text-2:#52514e;
   --busy:#2a78d6;--bp:#ec835a;--idle:#9a9a99;
   --flame:#2a78d6;--good:#0ca30c;--crit:#d03b3b}
 @media (prefers-color-scheme: dark){:root{color-scheme:dark;
   --surface:#1a1a19;--panel:#232322;--border:#3a3a38;
   --text:#fff;--text-2:#c3c2b7;
   --busy:#3987e5;--bp:#ec835a;--idle:#7a7a78;
   --flame:#3987e5;--good:#0ca30c;--crit:#d03b3b}}
 body{font-family:system-ui,sans-serif;margin:1.5rem;max-width:72rem;
   background:var(--surface);color:var(--text)}
 h1{font-size:1.25rem;margin:.2rem 0 1rem}
 h2{font-size:1rem;margin:1.4rem 0 .5rem;color:var(--text)}
 .tiles{display:flex;gap:.8rem;flex-wrap:wrap}
 .tile{background:var(--panel);border:1px solid var(--border);
   border-radius:8px;padding:.6rem .9rem;min-width:7.5rem}
 .tile .v{font-size:1.4rem;font-weight:600}
 .tile .l{font-size:.75rem;color:var(--text-2)}
 table{border-collapse:collapse;width:100%;font-size:.88rem}
 th,td{border-bottom:1px solid var(--border);padding:.35rem .6rem;
   text-align:left}
 th{color:var(--text-2);font-weight:500}
 tr.sel{background:var(--panel)} tr.job{cursor:pointer}
 code{background:var(--panel);padding:0 .3rem;border-radius:4px}
 .ratio{display:flex;height:12px;width:160px;border-radius:4px;
   overflow:hidden;gap:2px;background:var(--surface)}
 .ratio div{height:100%}
 .legend{display:flex;gap:1rem;font-size:.78rem;color:var(--text-2);
   margin:.3rem 0}
 .legend span::before{content:"";display:inline-block;width:10px;
   height:10px;border-radius:3px;margin-right:.35rem;
   background:var(--c);vertical-align:-1px}
 button{background:var(--panel);color:var(--text);
   border:1px solid var(--border);border-radius:6px;
   padding:.25rem .6rem;font-size:.8rem;cursor:pointer;margin-right:.3rem}
 button:hover{border-color:var(--text-2)}
 #flame svg{width:100%;background:var(--panel);border-radius:8px}
 #flame text{font:10px system-ui;fill:#fff;pointer-events:none}
 .panelbox{background:var(--panel);border:1px solid var(--border);
   border-radius:8px;padding:.4rem;overflow-x:auto}
 .bp-subtask{display:flex;align-items:center;gap:.6rem;margin:.2rem 0}
 .bp-label{font-size:.8rem;color:var(--text-2);min-width:7rem}
 .bp-pct{font-size:.75rem;color:var(--text-2)}
 .bp-bar{display:flex;height:10px;width:220px;border-radius:4px;
   overflow:hidden;background:var(--surface)}
 .bp-busy{background:var(--busy)} .bp-backpressured{background:var(--bp)}
 .bp-idle{background:var(--idle)}
 .bp-vertex h3{font-size:.85rem;margin:.5rem 0 .15rem}
 .ckpt-table{margin:.3rem 0}
 .state-RUNNING{color:var(--busy)} .state-FINISHED{color:var(--good)}
 .state-FAILED,.state-CANCELED{color:var(--crit)}
 .err{color:var(--crit);font-size:.85rem;white-space:pre-wrap}
</style></head><body>
<h1>flink-tpu dashboard</h1>
<div class="tiles" id="tiles"></div>
<h2>Jobs</h2>
<table id="jobs"><thead><tr><th>id</th><th>name</th><th>state</th>
<th>records in / out</th><th>checkpoints</th><th>actions</th></tr></thead>
<tbody></tbody></table>
<div id="detail" style="display:none">
 <h2>Vertices — <code id="selid"></code></h2>
 <div class="legend">
  <span style="--c:var(--busy)">busy</span>
  <span style="--c:var(--bp)">backpressured</span>
  <span style="--c:var(--idle)">idle</span></div>
 <table id="verts"><thead><tr><th>vertex</th><th>par</th><th>state</th>
 <th>records in / out</th><th>watermark</th><th>time share</th></tr></thead>
 <tbody></tbody>
 </table>
 <h2>Throughput (records/s per operator)</h2><div id="tput"></div>
 <h2>Job graph</h2><div id="dag" class="panelbox"></div>
 <h2>Subtask backpressure</h2><div id="bp"></div>
 <div id="qswrap" style="display:none"><h2>Queryable state</h2>
 <div id="qs" class="panelbox"></div></div>
 <h2>Latency (source&rarr;sink)</h2><div class="tiles" id="lat"></div>
 <div id="lathops"></div>
 <h2>Checkpoints</h2>
 <div id="ckview"></div>
 <div id="ckpts" style="font-size:.88rem;color:var(--text-2)"></div>
 <div id="exc"></div>
 <h2>Flame graph <button onclick="flame()">sample</button>
  <button onclick="flameSvg()">server svg</button></h2>
 <div id="flame"></div>
</div>
<script>
let sel=null;
const J=async p=>(await fetch(p)).json();
const esc=s=>String(s).replace(/[&<>"]/g,c=>({'&':'&amp;','<':'&lt;',
  '>':'&gt;','"':'&quot;'}[c]));
function tile(l,v){return `<div class="tile"><div class="v">${v}</div>`+
  `<div class="l">${l}</div></div>`}
async function refresh(){
  const ov=await J('/overview');
  const jobs=(await J('/jobs')).jobs;
  let tin=0,tout=0;const rows=[];
  for(const j of jobs){
    const d=await J('/jobs/'+j.id);const m=await J('/jobs/'+j.id+'/metrics');
    tin+=m.records_in;tout+=m.records_out;
    rows.push({j,d,m});
  }
  document.getElementById('tiles').innerHTML=
    tile('running',ov.jobs_running)+tile('finished',ov.jobs_finished)+
    tile('failed',ov.jobs_failed)+
    tile('records in',tin.toLocaleString())+
    tile('records out',tout.toLocaleString());
  const tb=document.querySelector('#jobs tbody');tb.innerHTML='';
  for(const {j,d,m} of rows){
    const tr=document.createElement('tr');
    tr.className='job'+(sel===j.id?' sel':'');
    tr.onclick=()=>{sel=j.id;refresh()};
    tr.innerHTML=`<td><code>${esc(j.id)}</code></td><td>${esc(j.name)}</td>`+
     `<td class="state-${esc(d.state)}">${esc(d.state)}</td>`+
     `<td>${m.records_in.toLocaleString()} / ${m.records_out.toLocaleString()}</td>`+
     `<td>${d.completed_checkpoints.length}</td>`+
     `<td><button onclick="act(event,'${esc(j.id)}','savepoints')">savepoint</button>`+
     `<button onclick="act(event,'${esc(j.id)}','stop')">stop</button>`+
     `<button onclick="cancelJob(event,'${esc(j.id)}')">cancel</button></td>`;
    tb.appendChild(tr);
  }
  if(sel===null&&rows.length)sel=rows[0].j.id;
  const cur=rows.find(r=>r.j.id===sel);
  document.getElementById('detail').style.display=cur?'':'none';
  if(!cur)return;
  document.getElementById('selid').textContent=sel;
  const vb=document.querySelector('#verts tbody');vb.innerHTML='';
  for(const v of cur.d.vertices){
    const pct=x=>(100*x).toFixed(1)+'%';
    const tr=document.createElement('tr');
    tr.innerHTML=`<td>${esc(v.id)}</td><td>${v.parallelism}</td>`+
     `<td>${esc((v.status||[]).join(','))}</td>`+
     `<td>${v.records_in.toLocaleString()} / ${v.records_out.toLocaleString()}</td>`+
     `<td>${v.watermark==null?'&mdash;':v.watermark.toLocaleString()}</td>`+
     `<td><div class="ratio" title="busy ${pct(v.busy_ratio)} · `+
     `backpressured ${pct(v.backpressure_ratio)} · idle ${pct(v.idle_ratio)}">`+
     `<div style="width:${v.busy_ratio*100}%;background:var(--busy)"></div>`+
     `<div style="width:${v.backpressure_ratio*100}%;background:var(--bp)"></div>`+
     `<div style="width:${v.idle_ratio*100}%;background:var(--idle)"></div>`+
     `</div></td>`;
    vb.appendChild(tr);
  }
  const lat=cur.m.latency_ms||{};
  document.getElementById('lat').innerHTML=['p50','p95','p99','max']
    .filter(k=>lat[k]!==undefined)
    .map(k=>tile(k,lat[k].toFixed(1)+' ms')).join('')||
    '<span style="color:var(--text-2);font-size:.85rem">no samples yet</span>';
  fetch('/jobs/'+sel+'/latency.html').then(r=>r.text())
    .then(t=>{document.getElementById('lathops').innerHTML=t});
  renderTput(await J('/jobs/'+sel+'/metrics/history'));
  const ck=await J('/jobs/'+sel+'/checkpoints');
  document.getElementById('ckpts').textContent=
    ck.count?('completed: '+ck.count):'none yet';
  // server-rendered views: DAG svg, per-subtask backpressure, and the
  // checkpoint drill-down table (replaces the old client-side renderer)
  fetch('/jobs/'+sel+'/plan.svg').then(r=>r.text())
    .then(t=>{document.getElementById('dag').innerHTML=t});
  fetch('/jobs/'+sel+'/backpressure.html').then(r=>r.text())
    .then(t=>{document.getElementById('bp').innerHTML=t});
  const qsw=document.getElementById('qswrap');
  if(cur.d.queryable){qsw.style.display='';
    fetch('/jobs/'+sel+'/queryable.html').then(r=>r.text())
      .then(t=>{document.getElementById('qs').innerHTML=t});
  }else qsw.style.display='none';
  fetch('/jobs/'+sel+'/checkpoints.html').then(r=>r.text())
    .then(t=>{document.getElementById('ckview').innerHTML=t});
  const ex=await J('/jobs/'+sel+'/exceptions');
  let exh='';
  if((ex.history||[]).length){
    exh='<h2>Exception history</h2>'+ex.history.slice(-8).reverse()
      .map(e=>'<div class="err">'+
        new Date(e.timestamp_ms).toLocaleTimeString()+' '+
        esc(e.task)+': '+esc(e.exception)+'</div>').join('');
  }
  document.getElementById('exc').innerHTML=(ex.root_exception?
    ('<h2>Root exception</h2><div class="err">'+esc(ex.root_exception)+
     '</div>'):'')+exh;
}
function renderTput(h){
  // per-vertex records/sec over time, derived from the sampled cumulative
  // counters (MetricStore analog); one sparkline row per operator
  const s=h.series||[];const el=document.getElementById('tput');
  if(s.length<2){el.innerHTML=
    '<span style="color:var(--text-2);font-size:.85rem">sampling…</span>';
    return}
  const ids=Object.keys(s[s.length-1].vertices);
  const W=560,H=36;let out='';
  for(const id of ids){
    const rates=[];
    for(let i=1;i<s.length;i++){
      const a=s[i-1],b=s[i];
      const va=a.vertices[id],vb=b.vertices[id];
      if(!va||!vb)continue;
      const dt=Math.max(1,(b.ts-a.ts))/1000;
      rates.push(Math.max(0,(vb.records_in-va.records_in)/dt));
    }
    if(!rates.length)continue;
    const mx=Math.max(1,...rates);
    const pts=rates.map((r,i)=>
      `${(i/(rates.length-1||1)*W).toFixed(1)},`+
      `${(H-2-(H-6)*r/mx).toFixed(1)}`).join(' ');
    const cur=rates[rates.length-1];
    out+='<div class="bp-subtask"><span class="bp-label" title="'+esc(id)+
      '">'+esc(id.length>14?id.slice(0,13)+'…':id)+'</span>'+
      `<svg width="${W}" height="${H}" style="background:var(--panel);`+
      `border-radius:6px"><polyline fill="none" stroke="var(--busy)" `+
      `stroke-width="1.5" points="${pts}"/></svg>`+
      '<span class="bp-pct">'+
      (cur>=1e6?(cur/1e6).toFixed(2)+'M':cur>=1e3?(cur/1e3).toFixed(1)+'k':
       cur.toFixed(0))+'/s · peak '+
      (mx>=1e6?(mx/1e6).toFixed(2)+'M':mx>=1e3?(mx/1e3).toFixed(1)+'k':
       mx.toFixed(0))+'/s</span></div>';
  }
  el.innerHTML=out||'<span style="color:var(--text-2)">no vertices</span>';
}
async function act(ev,id,verb){ev.stopPropagation();
  await fetch('/jobs/'+id+'/'+verb,{method:'POST'});refresh()}
async function cancelJob(ev,id){ev.stopPropagation();
  await fetch('/jobs/'+id,{method:'PATCH'});refresh()}
async function flame(){
  const t=await J('/jobs/'+sel+'/flamegraph');
  const H=16,rows=[];
  (function walk(n,x0,x1,d){if(d>=0)rows.push({n,x0,x1,d});
    let x=x0;for(const c of (n.children||[])){
      const w=(x1-x0)*(c.value/Math.max(1,n.value));
      walk(c,x,x+w,d+1);x+=w;}})(t,0,100,-1);
  const depth=Math.max(0,...rows.map(r=>r.d))+1;
  // sequential single-hue: depth shades the one flame hue
  const svg=['<svg viewBox="0 0 1000 '+(depth*(H+2))+'" '+
    'xmlns="http://www.w3.org/2000/svg">'];
  for(const r of rows){
    const w=(r.x1-r.x0)*10;if(w<1)continue;
    const o=0.45+0.55*(1-r.d/Math.max(1,depth));
    svg.push(`<g><rect x="${(r.x0*10).toFixed(1)}" y="${r.d*(H+2)}" `+
     `width="${w.toFixed(1)}" height="${H}" rx="3" `+
     `fill="var(--flame)" fill-opacity="${o.toFixed(2)}">`+
     `<title>${esc(r.n.name)} — ${r.n.value} samples</title></rect>`+
     (w>60?`<text x="${(r.x0*10+4).toFixed(1)}" y="${r.d*(H+2)+12}">`+
       esc(r.n.name.slice(0,Math.floor(w/7)))+'</text>':'')+'</g>');
  }
  svg.push('</svg>');
  document.getElementById('flame').innerHTML=svg.join('');
}
async function flameSvg(){
  const t=await (await fetch('/jobs/'+sel+'/flamegraph.svg')).text();
  document.getElementById('flame').innerHTML=t;
}
refresh();setInterval(refresh,2000);
</script></body></html>
"""
