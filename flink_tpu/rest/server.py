"""REST API + web dashboard.

Analog of the reference's REST endpoint (``RestServerEndpoint`` + ~100
typed handlers in ``runtime/rest/handler/job/*`` + the Angular dashboard of
``flink-runtime-web``): a threaded HTTP server over the MiniCluster's job
registry serving reference-shaped JSON plus a single-page dashboard that
polls it.

Endpoints:
  GET  /overview                      cluster overview
  GET  /jobs                          job listing
  GET  /jobs/<id>                     topology + per-vertex gauges
  GET  /jobs/<id>/checkpoints         completed checkpoint stats
  GET  /jobs/<id>/backpressure        busy/idle/backpressured per vertex
  GET  /jobs/<id>/metrics             numeric metrics incl. latency pcts
  GET  /jobs/<id>/exceptions          root failure cause
  GET  /jobs/<id>/flamegraph          sampled task-thread flame graph
  POST /jobs/<id>/savepoints          trigger a savepoint
  PATCH /jobs/<id>                    cancel
  GET  /                              dashboard (HTML)
"""

from __future__ import annotations

import json
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Any, Dict, List, Optional, Tuple

import numpy as np


class JobRegistry:
    """Named running/finished jobs the REST layer exposes."""

    def __init__(self):
        self._jobs: Dict[str, Tuple[str, Any]] = {}  # id -> (name, cluster)
        self._lock = threading.Lock()
        self._n = 0

    def register(self, name: str, cluster) -> str:
        with self._lock:
            self._n += 1
            job_id = f"job-{self._n:04d}"
            self._jobs[job_id] = (name, cluster)
            return job_id

    def jobs(self) -> List[Tuple[str, str, Any]]:
        with self._lock:
            return [(jid, name, c) for jid, (name, c) in self._jobs.items()]

    def get(self, job_id: str):
        with self._lock:
            return self._jobs.get(job_id)


def _percentiles(xs: List[float]) -> Dict[str, float]:
    if not xs:
        return {}
    a = np.asarray(xs)
    return {"p50": float(np.percentile(a, 50)),
            "p95": float(np.percentile(a, 95)),
            "p99": float(np.percentile(a, 99)),
            "max": float(a.max()), "count": len(xs)}


class RestServer:
    def __init__(self, registry: JobRegistry, host: str = "127.0.0.1",
                 port: int = 0, ssl_context=None,
                 auth_token: Optional[str] = None):
        """``ssl_context``: server-side TLS (``security.ssl.rest.enabled``
        analog); ``auth_token``: require ``Authorization: Bearer <token>``
        on every request."""
        self.registry = registry
        self._ssl = ssl_context
        registry_ref = registry
        token_ref = auth_token

        class Handler(BaseHTTPRequestHandler):
            def log_message(self, *a):  # quiet
                pass

            def parse_request(self):
                ok = super().parse_request()
                if ok and token_ref is not None:
                    import hmac as _hmac
                    got = self.headers.get("Authorization", "")
                    if not _hmac.compare_digest(got.encode(),
                                                f"Bearer {token_ref}".encode()):
                        self.send_error(401, "missing or wrong bearer token")
                        return False
                return ok

            def _send(self, obj, status: int = 200,
                      content_type: str = "application/json"):
                data = (obj if isinstance(obj, bytes)
                        else json.dumps(obj, default=str).encode())
                self.send_response(status)
                self.send_header("Content-Type", content_type)
                self.send_header("Content-Length", str(len(data)))
                self.end_headers()
                self.wfile.write(data)

            def _job(self, job_id: str):
                entry = registry_ref.get(job_id)
                if entry is None:
                    self._send({"error": f"no job {job_id}"}, 404)
                    return None
                return entry

            def do_GET(self):  # noqa: N802
                path = self.path.split("?")[0].rstrip("/")
                if path == "" or path == "/index.html":
                    return self._send(_DASHBOARD_HTML.encode(),
                                      content_type="text/html")
                if path == "/overview":
                    jobs = registry_ref.jobs()
                    states = [c.job_status()["state"] for _, _, c in jobs]
                    return self._send({
                        "jobs_total": len(jobs),
                        "jobs_running": states.count("RUNNING"),
                        "jobs_finished": states.count("FINISHED"),
                        "jobs_failed": states.count("FAILED")})
                if path == "/jobs":
                    return self._send({"jobs": [
                        {"id": jid, "name": name,
                         "state": c.job_status()["state"]}
                        for jid, name, c in registry_ref.jobs()]})
                m = re.match(r"^/jobs/([^/]+)(?:/(.*))?$", path)
                if not m:
                    return self._send({"error": "not found"}, 404)
                entry = self._job(m.group(1))
                if entry is None:
                    return
                name, cluster = entry
                sub = m.group(2) or ""
                status = cluster.job_status()
                if sub == "":
                    return self._send({"id": m.group(1), "name": name,
                                       **status})
                if sub == "checkpoints":
                    return self._send({
                        "completed": status["completed_checkpoints"],
                        "count": len(status["completed_checkpoints"])})
                if sub == "backpressure":
                    return self._send({"vertices": [
                        {"id": v["id"],
                         "busy": round(v["busy_ratio"], 4),
                         "idle": round(v["idle_ratio"], 4),
                         "backpressured": round(v["backpressure_ratio"], 4)}
                        for v in status["vertices"]]})
                if sub == "metrics":
                    return self._send({
                        "records_in": sum(v["records_in"]
                                          for v in status["vertices"]),
                        "records_out": sum(v["records_out"]
                                           for v in status["vertices"]),
                        "latency_ms": _percentiles(
                            cluster.sink_latencies_ms())})
                if sub == "exceptions":
                    return self._send({"root_exception": status["failure"]})
                if sub == "flamegraph":
                    from flink_tpu.rest.flamegraph import flamegraph
                    # scope to THIS job's subtask threads — concurrent jobs
                    # must not pollute each other's profiles
                    names = {f"task-{t.vertex_uid}-{t.subtask_index}"
                             for t in getattr(cluster, "_tasks", [])}
                    return self._send(flamegraph(duration_ms=150,
                                                 thread_names=names))
                return self._send({"error": f"unknown path {sub}"}, 404)

            def do_POST(self):  # noqa: N802
                path = self.path.rstrip("/")
                m = re.match(r"^/jobs/([^/]+)/(savepoints|stop)$", path)
                if not m:
                    return self._send({"error": "not found"}, 404)
                entry = self._job(m.group(1))
                if entry is None:
                    return
                _name, cluster = entry
                if m.group(2) == "stop":
                    # stop-with-savepoint (`flink stop` analog)
                    sp = cluster.stop_with_savepoint()
                    if sp is None:
                        return self._send({"status": "failed"}, 409)
                    return self._send({"status": "stopped",
                                       "checkpoint_id": sp})
                sp = cluster.savepoint()
                if sp is None:
                    return self._send({"status": "failed"}, 409)
                return self._send({"status": "completed", "checkpoint_id": sp})

            def do_PATCH(self):  # noqa: N802
                m = re.match(r"^/jobs/([^/]+)$", self.path.rstrip("/"))
                if not m:
                    return self._send({"error": "not found"}, 404)
                entry = self._job(m.group(1))
                if entry is None:
                    return
                entry[1].cancel()
                return self._send({"status": "cancelling"}, 202)

        self._server = ThreadingHTTPServer((host, port), Handler)
        if ssl_context is not None:
            self._server.socket = ssl_context.wrap_socket(
                self._server.socket, server_side=True)
        self.host, self.port = self._server.server_address
        self._thread = threading.Thread(target=self._server.serve_forever,
                                        name="rest-server", daemon=True)

    def start(self) -> "RestServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._server.shutdown()
        self._server.server_close()

    @property
    def url(self) -> str:
        scheme = "https" if self._ssl is not None else "http"
        return f"{scheme}://{self.host}:{self.port}"


_DASHBOARD_HTML = """<!DOCTYPE html>
<html><head><title>flink-tpu dashboard</title>
<style>
 body{font-family:system-ui,sans-serif;margin:2rem;color:#1a1a1a}
 h1{font-size:1.3rem} h2{font-size:1.05rem;margin-top:1.5rem}
 table{border-collapse:collapse;min-width:40rem}
 th,td{border:1px solid #ccc;padding:.35rem .6rem;text-align:left;font-size:.9rem}
 th{background:#f3f3f3}
 .bar{display:inline-block;height:.7rem;background:#4a7dbd;vertical-align:middle}
 .bp{background:#c0504d}.idle{background:#9a9a9a}
 code{background:#f5f5f5;padding:0 .25rem}
</style></head><body>
<h1>flink-tpu dashboard</h1>
<div id="overview"></div>
<h2>Jobs</h2><table id="jobs"><tr><th>id</th><th>name</th><th>state</th>
<th>records in/out</th><th>checkpoints</th></tr></table>
<h2>Vertices</h2><table id="verts"><tr><th>job</th><th>vertex</th>
<th>parallelism</th><th>busy / backpressured / idle</th></tr></table>
<script>
async function refresh(){
  const ov = await (await fetch('/overview')).json();
  document.getElementById('overview').textContent =
    `jobs: ${ov.jobs_total} (running ${ov.jobs_running}, finished `+
    `${ov.jobs_finished}, failed ${ov.jobs_failed})`;
  const jobs = (await (await fetch('/jobs')).json()).jobs;
  const jt = document.getElementById('jobs');
  const vt = document.getElementById('verts');
  jt.querySelectorAll('tr:not(:first-child)').forEach(r=>r.remove());
  vt.querySelectorAll('tr:not(:first-child)').forEach(r=>r.remove());
  for (const j of jobs){
    const d = await (await fetch(`/jobs/${j.id}`)).json();
    const m = await (await fetch(`/jobs/${j.id}/metrics`)).json();
    const row = jt.insertRow();
    row.innerHTML = `<td><code>${j.id}</code></td><td>${j.name}</td>`+
      `<td>${d.state}</td><td>${m.records_in} / ${m.records_out}</td>`+
      `<td>${d.completed_checkpoints.length}</td>`;
    for (const v of d.vertices){
      const r = vt.insertRow();
      const w = x => Math.round(x*120);
      r.innerHTML = `<td><code>${j.id}</code></td><td>${v.id}</td>`+
        `<td>${v.parallelism}</td>`+
        `<td><span class="bar" style="width:${w(v.busy_ratio)}px"></span>`+
        `<span class="bar bp" style="width:${w(v.backpressure_ratio)}px"></span>`+
        `<span class="bar idle" style="width:${w(v.idle_ratio)}px"></span></td>`;
    }
  }
}
refresh(); setInterval(refresh, 2000);
</script></body></html>
"""
