"""Thread-sampling flame graphs.

Analog of the reference's REST-triggered task sampling
(``ThreadInfoRequestCoordinator`` + ``JobVertexFlameGraphFactory`` rendered
by d3-flame-graph): sample every live thread's Python stack via
``sys._current_frames`` at a fixed interval, fold identical stacks, and
build the nested-tree JSON a flame graph renders from.
"""

from __future__ import annotations

import sys
import threading
import time
import traceback
from collections import Counter
from typing import Any, Dict, List, Optional


def sample_stacks(duration_ms: int = 200, interval_ms: int = 5,
                  thread_prefix: Optional[str] = None,
                  thread_names: Optional[set] = None) -> Counter:
    """Collapsed stack counter: 'frameA;frameB;frameC' -> samples.
    ``thread_names``: exact-name allowlist (per-job scoping); otherwise
    ``thread_prefix`` filters by prefix."""
    folded: Counter = Counter()
    deadline = time.monotonic() + duration_ms / 1000.0
    names = {t.ident: t.name for t in threading.enumerate()}
    while time.monotonic() < deadline:
        for tid, frame in sys._current_frames().items():
            name = names.get(tid, str(tid))
            if tid == threading.get_ident():
                continue  # skip the sampler itself
            if thread_names is not None:
                if name not in thread_names:
                    continue
            elif thread_prefix and not name.startswith(thread_prefix):
                continue
            stack = traceback.extract_stack(frame)
            key = ";".join(f"{f.name} ({f.filename.rsplit('/', 1)[-1]}"
                           f":{f.lineno})" for f in stack)
            folded[key] += 1
        time.sleep(interval_ms / 1000.0)
    return folded


def folded_to_tree(folded: Counter) -> Dict[str, Any]:
    """Collapsed stacks -> d3-flame-graph nested {name, value, children}."""
    root: Dict[str, Any] = {"name": "root", "value": 0, "children": {}}
    for stack, count in folded.items():
        root["value"] += count
        node = root
        for frame in stack.split(";"):
            child = node["children"].get(frame)
            if child is None:
                child = {"name": frame, "value": 0, "children": {}}
                node["children"][frame] = child
            child["value"] += count
            node = child

    def finalize(node: Dict[str, Any]) -> Dict[str, Any]:
        return {"name": node["name"], "value": node["value"],
                "children": [finalize(c) for c in node["children"].values()]}

    return finalize(root)


def flamegraph(duration_ms: int = 200, interval_ms: int = 5,
               thread_prefix: Optional[str] = "task-",
               thread_names: Optional[set] = None) -> Dict[str, Any]:
    return folded_to_tree(sample_stacks(duration_ms, interval_ms,
                                        thread_prefix, thread_names))
