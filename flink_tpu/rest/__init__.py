from flink_tpu.rest.server import RestServer

__all__ = ["RestServer"]
