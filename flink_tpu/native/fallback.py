"""Pure-Python fallbacks for the native layer (no compiler available).

Same API as the ctypes wrappers in :mod:`flink_tpu.native`; compression uses
zlib (stdlib) instead of FLZ — the block codec records the method byte so
readers dispatch correctly either way.
"""

from __future__ import annotations

import os
import pickle
import zlib
from typing import Dict, Iterator, Optional

import numpy as np


def lz_compress(data: bytes) -> bytes:
    # Marker handled by codec.py: fallback blocks are written as method=zlib.
    return zlib.compress(data, 1)


_U64 = (1 << 64) - 1


def delta_varint_encode(vals: np.ndarray) -> bytes:
    vals = np.asarray(vals, np.int64)
    out = bytearray()
    prev = 0
    for v in vals.tolist():
        # wrap the delta to int64 first (it can exceed the int64 range when
        # mixing large-magnitude values) — matches the native C++ wraparound
        d = (v - prev) & _U64
        if d >= 1 << 63:
            d -= 1 << 64
        prev = v
        z = ((d << 1) ^ (d >> 63)) & _U64
        while z >= 0x80:
            out.append((z & 0x7F) | 0x80)
            z >>= 7
        out.append(z)
    return bytes(out)


def delta_varint_decode(data: bytes, n: int) -> np.ndarray:
    out = np.empty(n, np.int64)
    pos = 0
    prev = 0
    for i in range(n):
        z = 0
        shift = 0
        while True:
            b = data[pos]
            pos += 1
            z |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
        d = (z >> 1) ^ -(z & 1)
        # interpret as signed 64-bit
        if d >= 1 << 63:
            d -= 1 << 64
        prev = (prev + d) & ((1 << 64) - 1)
        sv = prev if prev < 1 << 63 else prev - (1 << 64)
        out[i] = sv
        prev = sv
    return out


class PySpillStore:
    """Dict + pickle-file persistence; honors the same flush/reopen contract."""

    def __init__(self, directory: str, mem_budget: int):
        self.directory = directory
        self.mem_budget = mem_budget
        os.makedirs(directory, exist_ok=True)
        self._path = os.path.join(directory, "pystore.pkl")
        self._map: Dict[bytes, bytes] = {}
        if os.path.exists(self._path):
            with open(self._path, "rb") as f:
                self._map = pickle.load(f)

    def put(self, key: bytes, value: bytes) -> None:
        self._map[key] = value

    def get(self, key: bytes) -> Optional[bytes]:
        return self._map.get(key)

    def delete(self, key: bytes) -> bool:
        return self._map.pop(key, None) is not None

    def __len__(self) -> int:
        return len(self._map)

    def keys(self) -> Iterator[bytes]:
        yield from list(self._map)

    def mem_used(self) -> int:
        return sum(len(v) for v in self._map.values())

    def log_bytes(self) -> int:
        return 0

    def flush(self) -> None:
        tmp = self._path + ".tmp"
        with open(tmp, "wb") as f:
            pickle.dump(self._map, f)
        os.replace(tmp, self._path)

    def compact(self) -> int:
        return 0

    def close(self) -> None:
        pass


class PyRingBuffer:
    def __init__(self, capacity: int):
        from collections import deque
        self.capacity = capacity
        self._q = deque()
        self._used = 0

    def push(self, data: bytes) -> bool:
        if self._used + len(data) + 4 > self.capacity:
            return False
        self._q.append(data)
        self._used += len(data) + 4
        return True

    def pop(self) -> Optional[bytes]:
        if not self._q:
            return None
        d = self._q.popleft()
        self._used -= len(d) + 4
        return d

    def free_space(self) -> int:
        return self.capacity - self._used
