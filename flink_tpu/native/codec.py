"""Columnar RecordBatch wire codec ("FTB1").

The record serialization layer of the data plane — the analog of the
reference's ``SpanningRecordSerializer`` + Cython fast coders
(``RecordWriter.serializeRecord``, ``pyflink/fn_execution/coder_impl_fast.pyx``)
redesigned columnar: a batch serializes as a handful of compressed column
blocks instead of per-record length-prefixed tuples, so the cost is O(columns)
calls + memcpy-speed block compression, not O(records) dispatch.

Block format: ``method u8 | varint orig_len | varint payload_len | payload``
with method 0 = raw, 1 = FLZ (native), 2 = zlib (fallback), 3 = delta-varint
(int64 only).  Timestamps use delta-varint (they arrive nearly sorted).
"""

from __future__ import annotations

import pickle
import struct
import zlib
from typing import Optional, Tuple

import numpy as np

from flink_tpu.core.batch import RecordBatch
from flink_tpu.native import (delta_varint_decode, delta_varint_encode,
                              lz_compress, lz_decompress, native_available)

MAGIC = b"FTB1"
_RAW, _FLZ, _ZLIB, _DVAR = 0, 1, 2, 3
_MIN_COMPRESS = 64  # don't bother compressing tiny blocks


def _put_varint(out: bytearray, v: int) -> None:
    while v >= 0x80:
        out.append((v & 0x7F) | 0x80)
        v >>= 7
    out.append(v)


def _get_varint(data: bytes, pos: int) -> Tuple[int, int]:
    v = 0
    shift = 0
    while True:
        b = data[pos]
        pos += 1
        v |= (b & 0x7F) << shift
        if not b & 0x80:
            return v, pos
        shift += 7


def _put_block(out: bytearray, raw: bytes, compress: bool = True) -> None:
    method, payload = _RAW, raw
    if compress and len(raw) >= _MIN_COMPRESS:
        if native_available():
            c = lz_compress(raw)
            if len(c) < len(raw):
                method, payload = _FLZ, c
        else:
            c = zlib.compress(raw, 1)
            if len(c) < len(raw):
                method, payload = _ZLIB, c
    out.append(method)
    _put_varint(out, len(raw))
    _put_varint(out, len(payload))
    out += payload


def _put_i64_block(out: bytearray, vals: np.ndarray, compress: bool = True) -> None:
    enc = delta_varint_encode(vals)
    if len(enc) < vals.nbytes:
        # nested block: the delta-varint stream itself is often repetitive
        # (constant inter-arrival gaps) so it gets a second LZ pass
        out.append(_DVAR)
        _put_varint(out, vals.size)
        _put_block(out, enc, compress)
    else:
        _put_block(out, np.ascontiguousarray(vals, np.int64).tobytes(), compress)


def _get_block(data: bytes, pos: int) -> Tuple[bytes, int]:
    method = data[pos]
    pos += 1
    if method == _DVAR:
        n, pos = _get_varint(data, pos)
        enc, pos = _get_block(data, pos)
        return delta_varint_decode(enc, n).tobytes(), pos
    orig, pos = _get_varint(data, pos)
    plen, pos = _get_varint(data, pos)
    payload = data[pos:pos + plen]
    pos += plen
    if method == _RAW:
        return payload, pos
    if method == _FLZ:
        return lz_decompress(payload, orig), pos
    if method == _ZLIB:
        return zlib.decompress(payload), pos
    raise ValueError(f"unknown block method {method}")


def encode_batch(batch: RecordBatch, compress: bool = True) -> bytes:
    out = bytearray(MAGIC)
    flags = ((batch.timestamps is not None) |
             ((batch.key_ids is not None) << 1) |
             ((batch.key_groups is not None) << 2))
    out.append(flags)
    _put_varint(out, len(batch))
    _put_varint(out, len(batch.columns))
    if batch.timestamps is not None:
        _put_i64_block(out, np.asarray(batch.timestamps, np.int64), compress)
    if batch.key_ids is not None:
        _put_block(out, np.ascontiguousarray(batch.key_ids, np.int32).tobytes(), compress)
    if batch.key_groups is not None:
        _put_block(out, np.ascontiguousarray(batch.key_groups, np.int32).tobytes(), compress)
    for name, col in batch.columns.items():
        nb = name.encode()
        _put_varint(out, len(nb))
        out += nb
        a = np.asarray(col)
        if a.dtype == object:
            out.append(1)
            _put_block(out, pickle.dumps(list(a), protocol=4), compress)
        else:
            out.append(0)
            ds = a.dtype.str.encode()
            _put_varint(out, len(ds))
            out += ds
            _put_varint(out, a.ndim)
            for d in a.shape:
                _put_varint(out, d)
            if a.dtype == np.int64 and a.ndim == 1:
                _put_i64_block(out, a, compress)
            else:
                _put_block(out, np.ascontiguousarray(a).tobytes(), compress)
    return bytes(out)


def decode_batch(data: bytes) -> RecordBatch:
    if data[:4] != MAGIC:
        raise ValueError("bad batch magic")
    pos = 4
    flags = data[pos]
    pos += 1
    n, pos = _get_varint(data, pos)
    n_cols, pos = _get_varint(data, pos)
    ts = kid = kg = None
    if flags & 1:
        raw, pos = _get_block(data, pos)
        ts = np.frombuffer(raw, np.int64).copy()
    if flags & 2:
        raw, pos = _get_block(data, pos)
        kid = np.frombuffer(raw, np.int32).copy()
    if flags & 4:
        raw, pos = _get_block(data, pos)
        kg = np.frombuffer(raw, np.int32).copy()
    cols = {}
    for _ in range(n_cols):
        ln, pos = _get_varint(data, pos)
        name = data[pos:pos + ln].decode()
        pos += ln
        kind = data[pos]
        pos += 1
        if kind == 1:
            raw, pos = _get_block(data, pos)
            cols[name] = np.asarray(pickle.loads(raw), dtype=object)
        else:
            ln, pos = _get_varint(data, pos)
            dtype = np.dtype(data[pos:pos + ln].decode())
            pos += ln
            ndim, pos = _get_varint(data, pos)
            shape = []
            for _ in range(ndim):
                d, pos = _get_varint(data, pos)
                shape.append(d)
            raw, pos = _get_block(data, pos)
            cols[name] = np.frombuffer(raw, dtype).reshape(shape).copy()
    return RecordBatch(cols, ts, kid, kg)
