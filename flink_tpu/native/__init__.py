"""Native runtime layer loader (C++ via ctypes).

Builds ``native/flink_native.cc`` into a shared library on first use (g++,
cached by source hash) and exposes typed wrappers.  If no compiler is
available the pure-Python fallbacks in :mod:`flink_tpu.native.fallback` are
used transparently — same API, slower, and compression falls back to zlib
(method byte 2 in the block format, see :mod:`flink_tpu.native.codec`).

This is the TPU-native equivalent of the reference's native-performance
components (SURVEY §2.6): Cython fast coders, JNI LZ4 buffer compression,
RocksDB spill tier, off-heap network buffers.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
_SRC = os.path.join(_REPO_ROOT, "native", "flink_native.cc")
_BUILD_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), "_build")

_lib: Optional[ctypes.CDLL] = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None


def _build_and_load() -> Optional[ctypes.CDLL]:
    global _build_error
    if not os.path.exists(_SRC):
        _build_error = f"source not found: {_SRC}"
        return None
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    so_path = os.path.join(_BUILD_DIR, f"libflink_native_{digest}.so")
    if not os.path.exists(so_path):
        os.makedirs(_BUILD_DIR, exist_ok=True)
        tmp = so_path + f".tmp.{os.getpid()}"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC", "-pthread",
               "-fvisibility=hidden", "-o", tmp, _SRC]
        try:
            subprocess.run(cmd, check=True, capture_output=True, timeout=120)
            os.replace(tmp, so_path)  # atomic: concurrent builders race benignly
        except (subprocess.CalledProcessError, subprocess.TimeoutExpired,
                FileNotFoundError) as e:
            err = getattr(e, "stderr", b"") or b""
            _build_error = f"native build failed: {e}: {err.decode()[:500]}"
            return None
    lib = ctypes.CDLL(so_path)
    _declare(lib)
    return lib


def _declare(lib: ctypes.CDLL) -> None:
    i64, u8p, u32 = ctypes.c_int64, ctypes.POINTER(ctypes.c_uint8), ctypes.c_uint32
    vp, cp, cint = ctypes.c_void_p, ctypes.c_char_p, ctypes.c_int
    lib.fn_delta_varint_encode_i64.restype = i64
    lib.fn_delta_varint_encode_i64.argtypes = [ctypes.c_void_p, i64, u8p, i64]
    lib.fn_delta_varint_decode_i64.restype = i64
    lib.fn_delta_varint_decode_i64.argtypes = [u8p, i64, i64, ctypes.c_void_p]
    lib.fn_lz_bound.restype = i64
    lib.fn_lz_bound.argtypes = [i64]
    lib.fn_lz_compress.restype = i64
    lib.fn_lz_compress.argtypes = [u8p, i64, u8p, i64]
    lib.fn_lz_decompress.restype = i64
    lib.fn_lz_decompress.argtypes = [u8p, i64, u8p, i64]
    lib.fn_crc32.restype = u32
    lib.fn_crc32.argtypes = [u8p, i64, u32]
    lib.fn_crc32c.restype = u32
    lib.fn_crc32c.argtypes = [u8p, i64, u32]
    lib.spill_open.restype = vp
    lib.spill_open.argtypes = [cp, i64]
    lib.spill_put.restype = cint
    lib.spill_put.argtypes = [vp, u8p, i64, u8p, i64]
    lib.spill_get.restype = i64
    lib.spill_get.argtypes = [vp, u8p, i64, u8p, i64]
    lib.spill_delete.restype = cint
    lib.spill_delete.argtypes = [vp, u8p, i64]
    lib.spill_count.restype = i64
    lib.spill_count.argtypes = [vp]
    lib.spill_mem_used.restype = i64
    lib.spill_mem_used.argtypes = [vp]
    lib.spill_log_bytes.restype = i64
    lib.spill_log_bytes.argtypes = [vp]
    lib.spill_log_garbage.restype = i64
    lib.spill_log_garbage.argtypes = [vp]
    lib.spill_flush.restype = cint
    lib.spill_flush.argtypes = [vp]
    lib.spill_compact.restype = i64
    lib.spill_compact.argtypes = [vp]
    lib.spill_close.restype = None
    lib.spill_close.argtypes = [vp]
    lib.spill_iter_begin.restype = vp
    lib.spill_iter_begin.argtypes = [vp]
    lib.spill_iter_next.restype = i64
    lib.spill_iter_next.argtypes = [vp, u8p, i64]
    lib.spill_iter_end.restype = None
    lib.spill_iter_end.argtypes = [vp]
    lib.ring_create.restype = vp
    lib.ring_create.argtypes = [i64]
    lib.ring_free_space.restype = i64
    lib.ring_free_space.argtypes = [vp]
    lib.ring_push.restype = cint
    lib.ring_push.argtypes = [vp, u8p, i64]
    lib.ring_pop.restype = i64
    lib.ring_pop.argtypes = [vp, u8p, i64]
    lib.ring_destroy.restype = None
    lib.ring_destroy.argtypes = [vp]
    lib.keydict_create.restype = vp
    lib.keydict_create.argtypes = [i64]
    lib.keydict_destroy.restype = None
    lib.keydict_destroy.argtypes = [vp]
    lib.keydict_size.restype = i64
    lib.keydict_size.argtypes = [vp]
    lib.keydict_lookup_or_insert.restype = None
    lib.keydict_lookup_or_insert.argtypes = [vp, vp, i64, vp]
    lib.keydict_lookup.restype = None
    lib.keydict_lookup.argtypes = [vp, vp, i64, vp]
    lib.keydict_reverse.restype = None
    lib.keydict_reverse.argtypes = [vp, vp]
    i32 = ctypes.c_int32
    lib.wm_create.restype = vp
    lib.wm_create.argtypes = [vp, i32, u8p, u8p, vp]
    lib.wm_destroy.restype = None
    lib.wm_destroy.argtypes = [vp]
    lib.wm_drop_pane.restype = None
    lib.wm_drop_pane.argtypes = [vp, i64]
    lib.wm_pane_count.restype = i64
    lib.wm_pane_count.argtypes = [vp]
    lib.wm_live_panes.restype = None
    lib.wm_live_panes.argtypes = [vp, vp]
    lib.wm_probe_update.restype = None
    lib.wm_probe_update.argtypes = [vp, vp, vp, i64, vp, u8p, vp, i64, vp,
                                    i64, i32, i32]
    lib.wm_probe_update2.restype = None
    lib.wm_probe_update2.argtypes = [vp, vp, vp, i64, vp, u8p, vp, i64, vp,
                                     i64, i32, i32, i64, vp]
    lib.fn_hw_threads.restype = i32
    lib.fn_hw_threads.argtypes = []
    lib.wm_fire.restype = i64
    lib.wm_fire.argtypes = [vp, vp, i32, vp, vp, vp]
    lib.wm_export_pane.restype = i32
    lib.wm_export_pane.argtypes = [vp, i64, i64, vp, vp]
    lib.wm_import_pane.restype = None
    lib.wm_import_pane.argtypes = [vp, i64, i64, vp, vp]
    lib.wm_apply_delta.restype = None
    lib.wm_apply_delta.argtypes = [vp, i64, i64, vp, vp, u8p]


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first call; None if unavailable."""
    global _lib
    if _lib is not None:
        return _lib
    with _lib_lock:
        if _lib is None and _build_error is None:
            _lib = _build_and_load()
    return _lib


def native_available() -> bool:
    return get_lib() is not None


def build_error() -> Optional[str]:
    get_lib()
    return _build_error


def _u8(buf) -> "ctypes.POINTER(ctypes.c_uint8)":
    return (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf) if isinstance(buf, (bytes, bytearray)) else buf


# ---------------------------------------------------------------------------
# typed wrappers (native or fallback)
# ---------------------------------------------------------------------------

def lz_compress(data: bytes) -> bytes:
    lib = get_lib()
    if lib is None:
        from flink_tpu.native import fallback
        return fallback.lz_compress(data)
    n = len(data)
    cap = int(lib.fn_lz_bound(n))
    out = (ctypes.c_uint8 * cap)()
    w = lib.fn_lz_compress(_u8(data), n, out, cap)
    if w < 0:
        raise RuntimeError("lz_compress overflow")
    return bytes(out[:w])


def lz_decompress(data: bytes, orig_n: int) -> bytes:
    lib = get_lib()
    if lib is None:
        raise RuntimeError("FLZ decompression requires the native library: "
                           + str(_build_error))
    out = (ctypes.c_uint8 * max(orig_n, 1))()
    r = lib.fn_lz_decompress(_u8(data), len(data), out, orig_n)
    if r != orig_n:
        raise ValueError("malformed FLZ block")
    return bytes(out[:orig_n])


def delta_varint_encode(vals) -> bytes:
    import numpy as np
    vals = np.ascontiguousarray(vals, np.int64)
    lib = get_lib()
    if lib is None:
        from flink_tpu.native import fallback
        return fallback.delta_varint_encode(vals)
    cap = vals.size * 10 + 16
    out = (ctypes.c_uint8 * cap)()
    w = lib.fn_delta_varint_encode_i64(vals.ctypes.data_as(ctypes.c_void_p),
                                       vals.size, out, cap)
    if w < 0:
        raise RuntimeError("varint encode overflow")
    return bytes(out[:w])


def delta_varint_decode(data: bytes, n: int):
    import numpy as np
    lib = get_lib()
    if lib is None:
        from flink_tpu.native import fallback
        return fallback.delta_varint_decode(data, n)
    out = np.empty(n, np.int64)
    r = lib.fn_delta_varint_decode_i64(_u8(data), len(data), n,
                                       out.ctypes.data_as(ctypes.c_void_p))
    if r < 0:
        raise ValueError("malformed varint stream")
    return out


def crc32(data: bytes, seed: int = 0) -> int:
    lib = get_lib()
    if lib is None:
        import zlib
        return zlib.crc32(data, seed)
    return int(lib.fn_crc32(_u8(data), len(data), seed))


_CRC32C_TABLE = None


def crc32c(data: bytes, seed: int = 0) -> int:
    """CRC32C (Castagnoli) — Kafka v2 record-batch checksum."""
    lib = get_lib()
    if lib is not None and hasattr(lib, "fn_crc32c"):
        return int(lib.fn_crc32c(_u8(data), len(data), seed))
    global _CRC32C_TABLE
    if _CRC32C_TABLE is None:
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (0x82F63B78 ^ (c >> 1)) if c & 1 else c >> 1
            tbl.append(c)
        _CRC32C_TABLE = tbl
    c = seed ^ 0xFFFFFFFF
    for b in data:
        c = _CRC32C_TABLE[(c ^ b) & 0xFF] ^ (c >> 8)
    return c ^ 0xFFFFFFFF


class SpillStore:
    """Memory-budgeted KV store with disk spill (RocksDB-tier analog).

    Keys and values are ``bytes``. Values beyond ``mem_budget`` resident bytes
    are evicted (oldest-written first) to an append-only log;
    ``flush()`` persists a manifest so ``SpillStore(dir)`` reopens durable
    state; ``compact()`` reclaims dead log bytes.
    """

    def __init__(self, directory: str, mem_budget: int = 64 << 20):
        self._lib = get_lib()
        self.directory = directory
        if self._lib is None:
            from flink_tpu.native import fallback
            self._impl = fallback.PySpillStore(directory, mem_budget)
            self._h = None
        else:
            os.makedirs(directory, exist_ok=True)
            self._h = self._lib.spill_open(directory.encode(), mem_budget)
            if not self._h:
                raise RuntimeError(f"spill_open failed for {directory}")
            self._impl = None

    def put(self, key: bytes, value: bytes) -> None:
        if self._impl is not None:
            self._impl.put(key, value)
            return
        self._lib.spill_put(self._h, _u8(key), len(key), _u8(value), len(value))

    def get(self, key: bytes) -> Optional[bytes]:
        if self._impl is not None:
            return self._impl.get(key)
        cap = 4096
        while True:
            out = (ctypes.c_uint8 * cap)()
            n = self._lib.spill_get(self._h, _u8(key), len(key), out, cap)
            if n == -1:
                return None
            if n == -2:
                raise IOError("spill store read failed")
            if n <= cap:
                return bytes(out[:n])
            cap = int(n)

    def delete(self, key: bytes) -> bool:
        if self._impl is not None:
            return self._impl.delete(key)
        return bool(self._lib.spill_delete(self._h, _u8(key), len(key)))

    def __len__(self) -> int:
        if self._impl is not None:
            return len(self._impl)
        return int(self._lib.spill_count(self._h))

    def keys(self):
        if self._impl is not None:
            yield from self._impl.keys()
            return
        it = self._lib.spill_iter_begin(self._h)
        try:
            cap = 256
            buf = (ctypes.c_uint8 * cap)()
            while True:
                n = self._lib.spill_iter_next(it, buf, cap)
                if n == -1:
                    return
                if n > cap:
                    cap = int(n)
                    buf = (ctypes.c_uint8 * cap)()
                    continue
                yield bytes(buf[:n])
        finally:
            self._lib.spill_iter_end(it)

    def mem_used(self) -> int:
        if self._impl is not None:
            return self._impl.mem_used()
        return int(self._lib.spill_mem_used(self._h))

    def log_bytes(self) -> int:
        if self._impl is not None:
            return self._impl.log_bytes()
        return int(self._lib.spill_log_bytes(self._h))

    def flush(self) -> None:
        if self._impl is not None:
            self._impl.flush()
            return
        if self._lib.spill_flush(self._h) != 0:
            raise IOError("spill flush failed")

    def compact(self) -> int:
        if self._impl is not None:
            return self._impl.compact()
        r = int(self._lib.spill_compact(self._h))
        if r < 0:
            raise IOError("spill compact failed")
        return r

    def close(self) -> None:
        if self._impl is not None:
            self._impl.close()
            return
        if self._h:
            self._lib.spill_close(self._h)
            self._h = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()


class RingBuffer:
    """SPSC length-prefixed byte ring (host infeed / network buffer analog)."""

    def __init__(self, capacity: int = 1 << 20):
        self._lib = get_lib()
        if self._lib is None:
            from flink_tpu.native import fallback
            self._impl = fallback.PyRingBuffer(capacity)
            self._h = None
        else:
            self._h = self._lib.ring_create(capacity)
            self._impl = None

    def push(self, data: bytes) -> bool:
        if self._impl is not None:
            return self._impl.push(data)
        return bool(self._lib.ring_push(self._h, _u8(data), len(data)))

    def pop(self) -> Optional[bytes]:
        if self._impl is not None:
            return self._impl.pop()
        cap = 4096
        while True:
            out = (ctypes.c_uint8 * cap)()
            n = self._lib.ring_pop(self._h, out, cap)
            if n == -1:
                return None
            if n <= cap:
                return bytes(out[:n])
            cap = int(n)

    def free_space(self) -> int:
        if self._impl is not None:
            return self._impl.free_space()
        return int(self._lib.ring_free_space(self._h))

    def close(self) -> None:
        if self._impl is not None:
            return
        if self._h:
            self._lib.ring_destroy(self._h)
            self._h = None
