"""Two-input operators: connected streams + broadcast state pattern.

Analogs of the reference's ``CoStreamMap``/``CoProcessOperator``
(``TwoInputStreamOperator`` family) and the broadcast state pattern
(``CoBroadcastWithKeyedOperator`` + ``api/common/state/BroadcastState``):
input 0 is the main (possibly keyed) stream, input 1 the second/broadcast
side.  Batched: each side's batches arrive whole; the broadcast side is
replicated to every parallel subtask by the BROADCAST edge partitioning, so
each subtask holds an identical copy of the broadcast state — exactly the
reference's invariant.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

import numpy as np

from flink_tpu.core.batch import RecordBatch, StreamElement, Watermark
from flink_tpu.core.functions import RuntimeContext
from flink_tpu.operators.base import StreamOperator


class CoMapOperator(StreamOperator):
    """``connect().map(f1, f2)``: two row-wise transforms into one output
    stream (``CoStreamMap`` analog). Functions take/return column dicts."""

    is_two_input = True

    def __init__(self, fn1: Callable, fn2: Callable, name: str = "co-map"):
        self.fns = (fn1, fn2)
        self.name = name

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        cols = self.fns[input_index](dict(batch.columns))
        return [_with_ts(cols, batch)]

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)


def _with_ts(cols: Dict[str, Any], src_batch: RecordBatch) -> RecordBatch:
    """Rebuild a batch, keeping event-time timestamps when the fn preserved
    the row count (a size-changing fn cannot inherit per-row times)."""
    out = RecordBatch({k: np.asarray(v) for k, v in cols.items()})
    if src_batch.timestamps is not None and len(out) == len(src_batch):
        out = out.with_timestamps(np.asarray(src_batch.timestamps))
    return out


class CoFlatMapOperator(StreamOperator):
    """``connect().flat_map(f1, f2)``: each fn returns a columns dict (any
    row count) or None."""

    is_two_input = True

    def __init__(self, fn1: Callable, fn2: Callable, name: str = "co-flat-map"):
        self.fns = (fn1, fn2)
        self.name = name

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        cols = self.fns[input_index](dict(batch.columns))
        if cols is None:
            return []
        return [_with_ts(cols, batch)]

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)


class CoProcessFunction:
    """User function for ``connect().process()`` — batched
    ``CoProcessFunction`` analog. Override either side; return a columns
    dict (or None) to emit."""

    def open(self, ctx: RuntimeContext) -> None:
        pass

    def process_batch1(self, cols: Dict[str, Any], ctx) -> Optional[Dict[str, Any]]:
        return None

    def process_batch2(self, cols: Dict[str, Any], ctx) -> Optional[Dict[str, Any]]:
        return None

    def on_watermark(self, timestamp: int, ctx) -> Optional[Dict[str, Any]]:
        return None


class CoProcessOperator(StreamOperator):
    is_two_input = True

    def __init__(self, fn: CoProcessFunction, name: str = "co-process"):
        self.fn = fn
        self.name = name

    def open(self, ctx: RuntimeContext) -> None:
        super().open(ctx)
        self.fn.open(ctx)

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        handler = (self.fn.process_batch1 if input_index == 0
                   else self.fn.process_batch2)
        out = handler(dict(batch.columns), self)
        if out is None:
            return []
        return [_with_ts(out, batch)]

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        out = self.fn.on_watermark(watermark.timestamp, self)
        if out is None:
            return []
        return [RecordBatch({k: np.asarray(v) for k, v in out.items()})]


class BroadcastProcessFunction:
    """Batched ``KeyedBroadcastProcessFunction`` analog.

    ``process_batch(cols, broadcast_state, ctx)`` handles main-stream
    batches; ``process_broadcast_batch(cols, broadcast_state, ctx)`` updates
    the broadcast state (a plain dict replicated on every subtask).
    """

    def open(self, ctx: RuntimeContext) -> None:
        pass

    def process_batch(self, cols: Dict[str, Any],
                      broadcast_state: Dict[Any, Any],
                      ctx) -> Optional[Dict[str, Any]]:
        return None

    def process_broadcast_batch(self, cols: Dict[str, Any],
                                broadcast_state: Dict[Any, Any],
                                ctx) -> None:
        pass


class BroadcastConnectOperator(StreamOperator):
    """Main stream (input 0) + broadcast rule stream (input 1) with
    checkpointed broadcast state (``BroadcastState`` analog: each subtask
    keeps an identical copy because the edge replicates every rule batch)."""

    is_two_input = True

    def __init__(self, fn: BroadcastProcessFunction,
                 name: str = "broadcast-connect"):
        self.fn = fn
        self.name = name
        self.broadcast_state: Dict[Any, Any] = {}

    def open(self, ctx: RuntimeContext) -> None:
        super().open(ctx)
        self.fn.open(ctx)

    def process_batch2(self, batch: RecordBatch,
                       input_index: int) -> List[StreamElement]:
        if input_index == 1:
            self.fn.process_broadcast_batch(dict(batch.columns),
                                            self.broadcast_state, self)
            return []
        out = self.fn.process_batch(dict(batch.columns),
                                    self.broadcast_state, self)
        if out is None:
            return []
        return [_with_ts(out, batch)]

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return self.process_batch2(batch, 0)

    def snapshot_state(self) -> Dict[str, Any]:
        return {"broadcast_state": dict(self.broadcast_state)}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self.broadcast_state = dict(snap.get("broadcast_state", {}))
