"""Basic stream operators: map/filter/flatMap, keyBy, timestamps/watermarks,
keyed running reduce, and sinks — all batched.

Analogs: ``StreamMap``/``StreamFilter``/``StreamFlatMap``
(``flink-streaming-java/.../api/operators/``), the keying side of
``KeyedStream.java`` + ``KeyGroupStreamPartitioner``,
``TimestampsAndWatermarksOperator.java``, ``StreamGroupedReduceOperator``.
Each processes a whole ``RecordBatch`` per call; jax-traceable map/filter
bodies fuse into the surrounding device step (operator chaining,
``OperatorChain.java:88`` — on TPU, XLA does the fusing).
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from flink_tpu.core import keygroups
from flink_tpu.core.batch import RecordBatch, StreamElement, Watermark
from flink_tpu.core.functions import AggregateFunction, RuntimeContext
from flink_tpu.core.watermarks import WatermarkGenerator
from flink_tpu.operators.base import StreamOperator
from flink_tpu.ops.scatter import segment_running_fold
from flink_tpu.state.keyindex import make_key_index


class MapOperator(StreamOperator):
    """Vectorized map: fn(columns dict) -> columns dict (row-aligned)."""

    is_stateless = True

    def __init__(self, fn: Callable[[Dict[str, Any]], Dict[str, Any]],
                 name: str = "map"):
        self.fn = fn
        self.name = name

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return [batch.with_columns(self.fn(dict(batch.columns)))]


class FilterOperator(StreamOperator):
    """Vectorized filter: fn(columns) -> bool mask [B]."""

    is_stateless = True

    def __init__(self, fn: Callable[[Dict[str, Any]], np.ndarray],
                 name: str = "filter"):
        self.fn = fn
        self.name = name

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        mask = np.asarray(self.fn(dict(batch.columns)))
        if mask.all():
            return [batch]
        return [batch.select(mask)]


class FlatMapOperator(StreamOperator):
    """Vectorized flatMap: fn(columns) -> (new_columns, src_row_indices).

    ``src_row_indices`` (int array, len = output rows) says which input row
    produced each output row, so timestamps/keys propagate correctly.
    """

    is_stateless = True

    def __init__(self, fn: Callable[[Dict[str, Any]], Any], name: str = "flat-map"):
        self.fn = fn
        self.name = name

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        cols, src = self.fn(dict(batch.columns))
        src = np.asarray(src)
        ts = None if batch.timestamps is None else np.asarray(batch.timestamps)[src]
        kid = None if batch.key_ids is None else np.asarray(batch.key_ids)[src]
        kg = None if batch.key_groups is None else np.asarray(batch.key_groups)[src]
        return [RecordBatch(cols, ts, kid, kg)]


class KeyByOperator(StreamOperator):
    """Attach key-group routing metadata (``KeyGroupStreamPartitioner`` analog).

    Computes ``key_group = murmur(hash(key)) % max_parallelism`` per record —
    the unit both network routing and state sharding agree on, so rescaling
    moves whole key-group ranges (``KeyGroupRangeAssignment.java:50-84``).
    Dense per-key slot ids stay owned by the downstream stateful operator.
    """

    is_stateless = True

    def __init__(self, key_column: str, max_parallelism: int = 128,
                 name: str = "key-by"):
        self.key_column = key_column
        self.max_parallelism = max_parallelism
        self.name = name

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        keys = np.asarray(batch.column(self.key_column))
        kg = keygroups.assign_to_key_group(keygroups.hash_keys(keys),
                                           self.max_parallelism)
        return [batch.with_keys(batch.key_ids, kg)]


class TimestampsAndWatermarksOperator(StreamOperator):
    """Extract event timestamps + emit watermarks
    (``TimestampsAndWatermarksOperator.java`` analog, batched: the generator
    sees each batch's timestamp column once)."""

    forwards_watermarks = False  # this operator owns event time downstream

    def __init__(self, generator: WatermarkGenerator,
                 timestamp_column: Optional[str] = None,
                 timestamp_fn: Optional[Callable[[Dict[str, Any]], np.ndarray]] = None,
                 name: str = "timestamps-watermarks"):
        self.generator = generator
        self.timestamp_column = timestamp_column
        self.timestamp_fn = timestamp_fn
        self.name = name

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if self.timestamp_fn is not None:
            ts = np.asarray(self.timestamp_fn(dict(batch.columns)), np.int64)
        elif self.timestamp_column is not None:
            ts = np.asarray(batch.column(self.timestamp_column), np.int64)
        else:
            ts = batch.timestamps
        out: List[StreamElement] = [batch.with_timestamps(ts)]
        wm = self.generator.on_batch(ts)
        if wm is not None:
            out.append(Watermark(wm))
        return out

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        # Upstream watermarks are ignored — this operator owns event time —
        # EXCEPT MAX_WATERMARK (end of input), which is forwarded so bounded
        # jobs flush (reference: TimestampsAndWatermarksOperator.java
        # processWatermark, which passes only Long.MAX_VALUE through).
        from flink_tpu.core.batch import MAX_WATERMARK
        if watermark.timestamp >= MAX_WATERMARK:
            return [Watermark(MAX_WATERMARK)]
        return []

    def snapshot_state(self) -> Dict[str, Any]:
        # watermark generators carry max-seen-timestamp across restores
        return {"gen": dict(self.generator.__dict__)}

    def restore_state(self, snapshot: Dict[str, Any]) -> None:
        self.generator.__dict__.update(snapshot.get("gen", {}))


class KeyedReduceOperator(StreamOperator):
    """``keyBy().reduce(fn)`` — emits the running per-key fold for EVERY input
    record (``StreamGroupedReduceOperator`` semantics), computed batched:

    sort batch by dense key slot -> segmented inclusive ``associative_scan``
    -> combine each row's in-batch prefix with the key's persisted accumulator
    -> un-sort.  One jitted device step per batch instead of a per-record
    state-map probe (SURVEY §3.3 hot loop (c)).
    """

    def __init__(self, agg: AggregateFunction, key_column: str,
                 value_column: Optional[str] = None,
                 output_column: str = "result",
                 initial_key_capacity: int = 1 << 10,
                 name: str = "keyed-reduce"):
        self.agg = agg
        self.key_column = key_column
        self.value_column = value_column
        self.output_column = output_column
        self.name = name
        self.spec = agg.acc_spec()
        self._K = max(1 << 10, initial_key_capacity)
        self.key_index = None
        self._leaves = None

    def _ensure(self, keys: np.ndarray):
        if self.key_index is None:
            self.key_index = make_key_index(keys[0] if keys.ndim else keys)

    def _alloc(self, K: int):
        return tuple(
            jnp.broadcast_to(jnp.asarray(init, dtype), (K,) + tuple(shape)).copy()
            for init, shape, dtype in zip(self.spec.leaf_inits, self.spec.leaf_shapes,
                                          self.spec.leaf_dtypes))

    @partial(jax.jit, static_argnums=0)
    def _step(self, leaves, slot_ids, values):
        lifted = tuple(jax.tree_util.tree_leaves(self.agg.lift(values)))
        order, sids, is_end, prefix = segment_running_fold(
            slot_ids, lifted, self.agg.combine_leaves)
        K = leaves[0].shape[0]
        current = tuple(l[jnp.minimum(sids, K - 1)] for l in leaves)
        running = self.agg.combine_leaves(current, prefix)
        write_ids = jnp.where(is_end, sids, K)
        new_leaves = tuple(
            l.at[write_ids].set(r.astype(l.dtype), mode="drop")
            for l, r in zip(leaves, running))
        # un-sort the running values back to input row order
        inv = jnp.argsort(order)
        out = self.agg.get_result(self.spec.unflatten(
            tuple(r[inv] for r in running)))
        return new_leaves, out

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        keys = np.asarray(batch.column(self.key_column))
        self._ensure(keys)
        slot_ids = self.key_index.lookup_or_insert(keys)
        if self._leaves is None:
            self._leaves = self._alloc(self._K)
        while self.key_index.num_keys > self._K:
            newK = self._K * 2
            grown = self._alloc(newK)
            self._leaves = tuple(g.at[: self._K].set(l)
                                 for g, l in zip(grown, self._leaves))
            self._K = newK
        values = (batch.column(self.value_column) if self.value_column
                  else dict(batch.columns))
        # pad to pow2 batch size: variable hash-split batch sizes would
        # otherwise recompile _step per distinct size (static-shape rule).
        # Pad slots use the out-of-range sentinel K -> writes drop.
        B = len(batch)
        Bp = max(64, 1 << (B - 1).bit_length())
        if Bp != B:
            pad = Bp - B
            slot_ids = np.concatenate(
                [np.asarray(slot_ids), np.full(pad, self._K, np.int64)])
            values = jax.tree_util.tree_map(
                lambda a: np.concatenate(
                    [np.asarray(a),
                     np.zeros((pad,) + np.shape(a)[1:], np.asarray(a).dtype)]),
                values)
        self._leaves, out = self._step(self._leaves,
                                       jnp.asarray(slot_ids, jnp.int32), values)
        out = jax.tree_util.tree_map(lambda a: np.asarray(a)[:B], out)
        cols = dict(batch.columns)
        if isinstance(out, dict):
            cols.update(out)
        else:
            cols[self.output_column] = out
        return [RecordBatch(cols, batch.timestamps, batch.key_ids, batch.key_groups)]

    def snapshot_state(self) -> Dict[str, Any]:
        if self.key_index is None:
            return {"empty": True}
        return {
            "empty": False,
            "keys": self.key_index.snapshot(),
            "key_index_kind": type(self.key_index).__name__,
            "leaves": [np.asarray(l)[: self.key_index.num_keys]
                       for l in self._leaves],
        }

    def restore_state(self, snap: Dict[str, Any]) -> None:
        from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex
        if snap.get("empty", True):
            return
        cls = (ObjectKeyIndex if snap["key_index_kind"] == "ObjectKeyIndex"
               else KeyIndex)
        self.key_index = cls.restore(snap["keys"])
        while self._K < self.key_index.num_keys:
            self._K *= 2
        self._leaves = self._alloc(self._K)
        n = snap["leaves"][0].shape[0]
        self._leaves = tuple(l.at[:n].set(jnp.asarray(s))
                             for l, s in zip(self._leaves, snap["leaves"]))


class SideOutputOperator(StreamOperator):
    """Consumes one side output tag (``DataStream.getSideOutput`` analog):
    unwraps matching TaggedBatch elements, drops the main stream."""

    def __init__(self, tag: str, name: str = "side-output"):
        self.accepts_tag = tag
        self.name = name

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        return []  # main-stream data does not pass

    def process_tagged(self, batch: RecordBatch) -> List[StreamElement]:
        return [batch]


class SinkOperator(StreamOperator):
    """Terminal operator wrapping a sink function (``StreamSink`` analog)."""

    def __init__(self, sink, name: str = "sink"):
        import copy as _copy

        # transactional/stateful sinks declare clone_per_subtask: each
        # parallel operator instance needs its OWN epoch buffers and txn
        # identity (a shared instance races across subtask threads and
        # breaks barrier alignment); collection-style sinks stay shared
        if getattr(sink, "clone_per_subtask", False):
            sink = _copy.deepcopy(sink)
            on_cloned = getattr(sink, "on_cloned", None)
            if on_cloned is not None:
                on_cloned()
        self.sink = sink
        self.name = name

    def open(self, ctx: RuntimeContext) -> None:
        super().open(ctx)
        if hasattr(self.sink, "open"):
            self.sink.open(ctx)

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        self.sink.write_batch(batch)
        return []

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        if hasattr(self.sink, "on_watermark"):
            self.sink.on_watermark(watermark.timestamp)
        return []

    def on_latency_marker(self, marker) -> None:
        """Source→sink latency sample (``LatencyStats`` at the sink).
        Reads through the clock seam so ClockSkew chaos covers latency
        tracking; skew-negative samples clamp to 0."""
        from flink_tpu.utils import clock

        self.latencies_ms = getattr(self, "latencies_ms", [])
        self.latencies_ms.append(max(
            0.0, (clock.now_ms_f() / 1000.0 - marker.marked_time) * 1000.0))
        if len(self.latencies_ms) > 1024:
            del self.latencies_ms[:512]

    def end_input(self) -> List[StreamElement]:
        # transactional sinks finalize on end-of-stream (commit the last
        # epoch's transaction — TwoPhaseCommitSink.end_input); without
        # this the tail between the final barrier and end-of-input stays
        # staged forever and close() ABORTS it: committed-output loss on
        # every bounded job (found gating the scenario suite, ISSUE-15)
        if hasattr(self.sink, "end_input"):
            self.sink.end_input()
        elif hasattr(self.sink, "flush"):
            self.sink.flush()
        return []

    # two-phase-commit sinks (FileSink/LogSink) hook the checkpoint lifecycle
    def snapshot_state(self) -> Dict[str, Any]:
        if hasattr(self.sink, "snapshot_state"):
            return self.sink.snapshot_state()
        return {}

    def restore_state(self, snapshot: Dict[str, Any]) -> None:
        if snapshot and hasattr(self.sink, "restore_state"):
            self.sink.restore_state(snapshot)

    def notify_checkpoint_complete(self, checkpoint_id: int) -> None:
        if hasattr(self.sink, "notify_checkpoint_complete"):
            self.sink.notify_checkpoint_complete(checkpoint_id)

    def close(self) -> None:
        if hasattr(self.sink, "close"):
            self.sink.close()


class ExtremumByOperator(StreamOperator):
    """``KeyedStream.minBy/maxBy`` analog: per key, keep the FULL ROW of the
    extreme element seen so far (ties keep the first arrival, the
    reference's ``minBy(field, first=true)``), emitting the current extreme
    per touched key per micro-batch with the TRIGGERING record's timestamp
    (``StreamGroupedReduceOperator`` emission semantics).  State follows the
    repo keyed-snapshot convention (key index + slot-aligned row fields) so
    rescale split/merge redistributes it by key group."""

    def __init__(self, key_column: str, value_column: str, is_min: bool,
                 name: str = "extremum-by"):
        self.key_column = key_column
        self.value_column = value_column
        self.is_min = is_min
        self.name = name
        self.key_index = None
        self._vals = np.zeros(0, np.float64)   # slot -> extreme value
        self._rows = np.zeros(0, object)       # slot -> extreme row dict

    def _ensure(self, n: int) -> None:
        if n > self._vals.size:
            cap = max(n, max(16, self._vals.size * 2))
            sentinel = np.inf if self.is_min else -np.inf
            nv = np.full(cap, sentinel, np.float64)
            nv[: self._vals.size] = self._vals
            nr = np.empty(cap, object)
            nr[: self._rows.size] = self._rows
            self._vals, self._rows = nv, nr

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        from flink_tpu.state.keyindex import make_key_index

        if len(batch) == 0:
            return []
        # NaN values can never win (a stored NaN would poison strict
        # comparisons forever); rows carrying NaN are ignored entirely
        vals_all = np.asarray(batch.column(self.value_column), np.float64)
        finite = ~np.isnan(vals_all)
        if not finite.all():
            batch = batch.select(finite)
            if len(batch) == 0:
                return []
        n = len(batch)
        keys = np.asarray(batch.column(self.key_column))
        vals = np.asarray(batch.column(self.value_column), np.float64)
        ts = (np.asarray(batch.timestamps)
              if batch.timestamps is not None else None)
        if self.key_index is None:
            self.key_index = make_key_index(keys[0] if keys.ndim else keys)
        slots = self.key_index.lookup_or_insert(keys).astype(np.int64)
        self._ensure(self.key_index.num_keys)
        _uniq, inv = np.unique(slots, return_inverse=True)
        # batch-local extreme per key: lexsort by (key group, value,
        # arrival) — the first row of each group is the winner
        sort_vals = vals if self.is_min else -vals
        order = np.lexsort((np.arange(n), sort_vals, inv))
        first = np.ones(n, bool)
        first[1:] = inv[order][1:] != inv[order][:-1]
        winners = order[first]
        rows = batch.take(winners).to_rows()
        out_rows: List[Dict[str, Any]] = []
        out_ts: List[int] = []
        better = (lambda a, b: a < b) if self.is_min else (lambda a, b: a > b)
        for row, w in zip(rows, winners.tolist()):
            slot = int(slots[w])
            v = float(vals[w])
            if self._rows[slot] is None or better(v, self._vals[slot]):
                self._vals[slot] = v
                self._rows[slot] = row
            out_rows.append(self._rows[slot])
            # emission carries the TRIGGERING record's timestamp: the
            # stored extreme may be arbitrarily behind the watermark
            out_ts.append(int(ts[w]) if ts is not None else 0)
        out = RecordBatch.from_rows(
            out_rows, timestamps=out_ts if ts is not None else None)
        return [out]

    def snapshot_state(self) -> Dict[str, Any]:
        if self.key_index is None:
            return {"empty": True}
        n = self.key_index.num_keys
        return {"empty": False,
                "keys": self.key_index.snapshot(),
                "key_index_kind": type(self.key_index).__name__,
                "state.vals": self._vals[:n].copy(),
                "state.rows": self._rows[:n].copy()}

    def restore_state(self, snap: Dict[str, Any]) -> None:
        from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex

        if snap.get("empty", True):
            return
        cls = (ObjectKeyIndex if snap["key_index_kind"] == "ObjectKeyIndex"
               else KeyIndex)
        self.key_index = cls.restore(snap["keys"])
        n = self.key_index.num_keys
        self._ensure(n)
        self._vals[:n] = np.asarray(snap["state.vals"])
        self._rows[:n] = np.asarray(snap["state.rows"], object)
