"""Sliding count windows: ``countWindow(size, slide)``.

The reference composes this as GlobalWindows + ``CountTrigger.of(slide)``
+ ``CountEvictor.of(size)``
(``WindowedStream.countWindow(size, slide)`` in
``flink-streaming-java/.../api/datastream/WindowedStream.java``): every
``slide`` elements per key, emit the aggregate of that key's LAST
``size`` elements.  Round 3 rejected this combination (purging count
triggers can't share sliding panes); this operator implements it
directly with the TPU-runtime state shape instead of trigger+evictor
composition:

- per key, a **ring of the last ``size`` values** (dense ``[K, size]``,
  write position = arrival_count %% size — the ring IS the CountEvictor),
- an arrival counter and a fired-multiple register per key (the
  CountTrigger's ``ReducingState<Long>`` analog),
- vectorized batch fold: per-key ranks within the batch come from one
  stable argsort; the ring scatter is one fancy assignment (duplicate
  (key, pos) writes resolve last-wins = arrival order).

Mini-batch semantics (the repo's count-trigger convention, matching the
SQL bundle operators): fires are evaluated once per micro-batch — a key
crossing several ``slide`` multiples inside one batch fires ONCE with
its latest ring, not once per multiple.  Aggregates must declare numpy
twins (every built-in does); ring combine order is irrelevant because
the combine is commutative by contract.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

import numpy as np

from flink_tpu.core.batch import RecordBatch, StreamElement, Watermark
from flink_tpu.core.functions import (SCATTER_UFUNCS, AggregateFunction,
                                      RuntimeContext)
from flink_tpu.operators.base import StreamOperator
from flink_tpu.state.keyindex import KeyIndex, ObjectKeyIndex, make_key_index


class CountSlideWindowOperator(StreamOperator):
    """``key_by(k).count_window(size, slide).aggregate(agg)``."""

    def __init__(self, agg: AggregateFunction, key_column: str,
                 value_column: str, size: int, slide: int,
                 output_column: str = "result",
                 initial_key_capacity: int = 1 << 10,
                 name: str = "count-slide-window"):
        if size <= 0 or slide <= 0:
            raise ValueError("count_window size and slide must be positive")
        if not agg.supports_host_emit():
            raise ValueError("count_window(size, slide) needs an aggregate "
                             "with numpy twins (all built-ins qualify)")
        self.agg = agg
        self.kinds = agg.scatter_kind_leaves()
        self.spec = agg.acc_spec()
        self.key_column = key_column
        self.value_column = value_column
        self.size = int(size)
        self.slide = int(slide)
        self.output_column = output_column
        self.name = name
        self._K = max(64, initial_key_capacity)
        self.key_index: Optional[KeyIndex | ObjectKeyIndex] = None
        self._ring: Optional[np.ndarray] = None      # f64 [K, size]
        self._count: Optional[np.ndarray] = None     # i64 [K]
        self._fired: Optional[np.ndarray] = None     # i64 [K] slide multiples

    def open(self, ctx: RuntimeContext) -> None:
        pass

    def _ensure(self, n_keys: int) -> None:
        while self._K < n_keys:
            self._K <<= 1
        if self._ring is None:
            self._ring = np.zeros((self._K, self.size), np.float64)
            self._count = np.zeros(self._K, np.int64)
            self._fired = np.zeros(self._K, np.int64)
        elif self._ring.shape[0] < self._K:
            old = self._ring.shape[0]
            ring = np.zeros((self._K, self.size), np.float64)
            ring[:old] = self._ring
            self._ring = ring
            self._count = np.concatenate(
                [self._count, np.zeros(self._K - old, np.int64)])
            self._fired = np.concatenate(
                [self._fired, np.zeros(self._K - old, np.int64)])

    def process_batch(self, batch: RecordBatch) -> List[StreamElement]:
        if len(batch) == 0:
            return []
        keys = np.asarray(batch.column(self.key_column))
        vals = np.asarray(batch.column(self.value_column), np.float64)
        if self.key_index is None:
            self.key_index = make_key_index(keys[0] if keys.ndim else keys,
                                            capacity_hint=self._K)
        slots = np.asarray(self.key_index.lookup_or_insert(keys), np.int64)
        self._ensure(self.key_index.num_keys)
        n = len(slots)
        # per-key rank within the batch (arrival order): stable sort groups
        order = np.argsort(slots, kind="stable")
        ss = slots[order]
        starts = np.r_[True, ss[1:] != ss[:-1]]
        gstart = np.maximum.accumulate(np.where(starts, np.arange(n), 0))
        rank_sorted = np.arange(n) - gstart
        rank = np.empty(n, np.int64)
        rank[order] = rank_sorted
        pos = (self._count[slots] + rank) % self.size
        # fancy assignment in ARRIVAL order: duplicate (slot, pos) pairs
        # (a key receiving > size rows in one batch laps its ring) resolve
        # last-write-wins = the newest element, the CountEvictor semantics
        self._ring[slots, pos] = vals
        self._count[: self._K] += np.bincount(
            slots, minlength=self._K)[: self._K]
        # fire keys that crossed >= 1 slide multiple (mini-batch semantics)
        nk = self.key_index.num_keys
        mult = self._count[:nk] // self.slide
        fire = np.flatnonzero(mult > self._fired[:nk])
        if fire.size == 0:
            return []
        self._fired[:nk][fire] = mult[fire]
        return self._emit(fire)

    def _emit(self, fire: np.ndarray) -> List[StreamElement]:
        rows = self._ring[fire]                      # [m, size]
        valid = (np.arange(self.size)[None, :]
                 < np.minimum(self._count[fire], self.size)[:, None])
        lifted = self.agg.host_lift(rows.reshape(-1))
        leaves = []
        import jax
        for leaf, kind in zip(jax.tree_util.tree_leaves(lifted), self.kinds):
            leaf = np.asarray(leaf).reshape(fire.size, self.size)
            ident = self._identity(kind, leaf.dtype)
            masked = np.where(valid, leaf, ident)
            leaves.append(SCATTER_UFUNCS[kind].reduce(masked, axis=1))
        result = self.agg.host_get_result(self.spec.unflatten(leaves))
        raw_keys = np.asarray(self.key_index.reverse_keys())[fire]
        cols: Dict[str, Any] = {self.key_column: raw_keys}
        if isinstance(result, dict):
            cols.update(result)
        else:
            cols[self.output_column] = result
        return [RecordBatch(cols)]

    @staticmethod
    def _identity(kind: str, dtype) -> Any:
        if kind == "add":
            return np.zeros((), dtype)
        if np.issubdtype(dtype, np.integer):
            info = np.iinfo(dtype)
            return dtype.type(info.max if kind == "min" else info.min)
        return np.float64(np.inf if kind == "min" else -np.inf)

    def process_watermark(self, watermark: Watermark) -> List[StreamElement]:
        return []                       # counts, not time, drive fires

    def end_input(self) -> List[StreamElement]:
        # trailing partial slide emits nothing — reference drops partial
        # countWindows at end of input
        return []

    # ------------------------------------------------------------ snapshots
    def snapshot_state(self) -> Dict[str, Any]:
        snap: Dict[str, Any] = {}
        if self.key_index is not None:
            snap["key_index"] = self.key_index.snapshot()
            snap["key_index_kind"] = type(self.key_index).__name__
            n = self.key_index.num_keys
            snap["ring"] = self._ring[:n].copy()
            snap["count"] = self._count[:n].copy()
            snap["fired"] = self._fired[:n].copy()
        return snap

    def restore_state(self, snap: Dict[str, Any]) -> None:
        self._ring = None
        self.key_index = None
        if "key_index" not in snap:
            return
        if snap["key_index_kind"] == "ObjectKeyIndex":
            self.key_index = ObjectKeyIndex.restore(snap["key_index"])
        else:
            self.key_index = KeyIndex.restore(snap["key_index"])
        n = self.key_index.num_keys
        self._ensure(max(n, 1))
        self._ring[:n] = snap["ring"]
        self._count[:n] = snap["count"]
        self._fired[:n] = snap["fired"]
